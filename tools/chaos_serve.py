#!/usr/bin/env python
"""Serving chaos harness — the crash x drain x fault recovery matrix
for the serving daemon, producing the CHAOS_SERVE_r16.json round
artifact (round 16 tentpole).

Where tools/chaos_suite.py injures a supervised RUN, this tool injures
the serving TIER and grades what the round-16 resilience machinery
(serving/journal.py + the daemon's drain/takeover paths) recovers:

  kill_midburst_takeover   SIGKILL the daemon with a burst of acked
                           (journaled) requests still queued; a
                           `--takeover` successor must replay every
                           un-retired entry with ZERO acked loss and
                           BIT-IDENTICAL outputs (the per-request PRNG
                           / luma-bucket isolation contract is what
                           makes replay deterministic)
  drain_handoff            POST /drain with a request in flight: the
                           in-flight response must be delivered, new
                           requests must 503 with Retry-After, the
                           process must exit 0, and the flight dump
                           must carry reason=drain (not sigterm)
  serve_crash_torn         IA_FAULT_PLAN=serve_crash hard-kills the
                           daemon BETWEEN journal append and ack, a
                           torn half-line is appended to the journal,
                           and the takeover must still replay cleanly
  serve_diskfull           journal write failure is COUNTED (errors
                           gauge), never raised: the request still
                           serves 200
  serve_hang               an injected dispatcher hang is BOUNDED by
                           --dispatch-deadline-s: the batch fails 500
                           and the daemon keeps serving
  serve_evict              a forced cache-epoch eviction yields an
                           honest recompile (miss), never a wrong
                           answer
  lattice_shape_burst      (round 20) kill_midburst with RANDOM-shaped
                           frames under --lattice: the journal stores
                           raw frames, the same-spec takeover
                           re-buckets each replay at admission, and
                           zero-acked-loss + bit-identity must hold
                           across bucket boundaries and the bypass
                           path

Usage:
    JAX_PLATFORMS=cpu python tools/chaos_serve.py
        [--out CHAOS_SERVE_r16.json] [--size 24]

tools/check_chaos_serve.py validates the artifact; tier-1
(tests/test_resilience.py) validates the COMMITTED artifact and
tools/check_trajectory.py holds its headline cells (acked_loss,
recovery_warm_ms, replay_bit_identical) across rounds.
"""

from __future__ import annotations

import argparse
import base64
import hashlib
import json
import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
)

CHAOS_SERVE_SCHEMA_VERSION = 1

_SERVE_FLAGS = [
    "--levels", "2", "--matcher", "patchmatch",
    "--em-iters", "1", "--pm-iters", "2", "--device", "cpu",
    "--max-batch", "1", "--max-wait-ms", "5",
    "--max-queue-depth", "8",
]


def _proxy_frames(size: int, n: int):
    import numpy as np

    rng = np.random.default_rng(16)
    a = rng.random((size, size, 3)).astype(np.float32)
    ap = rng.random((size, size, 3)).astype(np.float32)
    frames = [
        rng.random((size, size, 3)).astype(np.float32)
        for _ in range(n)
    ]
    return a, ap, frames


def _body(frame) -> bytes:
    import numpy as np

    return json.dumps({
        "image_b64": base64.b64encode(
            np.ascontiguousarray(frame.astype(np.float32)).tobytes()
        ).decode(),
        "shape": list(frame.shape),
        "dtype": "float32",
    }).encode()


def _post(url: str, body: bytes, rid=None, timeout: float = 300.0):
    hdrs = {"Content-Type": "application/json"}
    if rid:
        hdrs["X-Request-Id"] = rid
    req = urllib.request.Request(
        url + "/synthesize", data=body, method="POST", headers=hdrs
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read()), dict(
                resp.headers
            )
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def _get_json(url: str, timeout: float = 30.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read())


def _response_sha(resp: dict) -> str:
    return hashlib.sha256(
        base64.b64decode(resp["image_b64"])
    ).hexdigest()


def _spawn_serve(a_path, ap_path, trace_dir, *, state_dir=None,
                 takeover=None, extra=(), env_extra=None):
    """One `ia-synth serve` subprocess; returns (proc, url) after the
    live.json rendezvous (which the CLI orders AFTER warmup/restore)."""
    cmd = [
        sys.executable, "-m", "image_analogies_tpu.cli", "serve",
        "--a", a_path, "--ap", ap_path, "--port", "0",
        "--trace-dir", trace_dir, *_SERVE_FLAGS, *extra,
    ]
    if state_dir:
        cmd += ["--state-dir", state_dir]
    if takeover:
        cmd += ["--takeover", takeover]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.update(env_extra or {})
    proc = subprocess.Popen(
        cmd, env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    live_path = os.path.join(trace_dir, "live.json")
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline:
        if os.path.isfile(live_path):
            with open(live_path) as f:
                return proc, json.load(f)["url"]
        if proc.poll() is not None:
            raise RuntimeError(
                f"serve subprocess exited rc={proc.returncode} "
                "before announcing"
            )
        time.sleep(0.1)
    proc.kill()
    raise RuntimeError("serve subprocess never announced live.json")


def _reap(proc):
    if proc.poll() is None:
        proc.kill()
    try:
        proc.wait(timeout=60)
    except subprocess.TimeoutExpired:
        pass


def _burst(url, bodies):
    """Fire the bodies concurrently; collect whatever responses come
    back (a killed daemon leaves None entries)."""
    results = [None] * len(bodies)

    def worker(i, rid, body):
        try:
            results[i] = _post(url, body, rid=rid)
        except Exception:  # noqa: BLE001 - the daemon was killed
            results[i] = None

    threads = []
    for i, (rid, body) in enumerate(bodies):
        t = threading.Thread(target=worker, args=(i, rid, body))
        t.start()
        threads.append(t)
        time.sleep(0.02)
    return threads, results


def _takeover_and_verify(a_path, ap_path, state_dir, frames_by_rid,
                         min_pending: int, extra=()):
    """Spawn a --takeover successor, wait for the replay backlog to
    hit zero, then re-post each replayed request's frame fresh and
    compare hashes.  Returns the arm's measurement dict.

    ``extra`` rides through to the successor's CLI flags — the lattice
    arm needs the SAME `--lattice` spec on both sides of the takeover,
    because the journal stores RAW frames and replay re-buckets them
    at admission (a successor on a different spec would key replays
    onto different executables and break bit-identity honestly).

    ``pending_at_takeover`` is measured from the dead predecessor's
    journal ON DISK (the daemon's own torn-tolerant scanner), not from
    the successor's /journal: with observed-warmup the replays are
    excache hits and can retire before the successor even announces."""
    from image_analogies_tpu.serving.journal import (
        RequestJournal, journal_path,
    )

    disk = RequestJournal(journal_path(state_dir)).counts()
    trace_b = tempfile.mkdtemp(prefix="ia_chaos_takeover_")
    t0 = time.monotonic()
    proc, url = _spawn_serve(
        a_path, ap_path, trace_b, takeover=state_dir, extra=extra
    )
    try:
        deadline = time.monotonic() + 300
        snap = None
        while time.monotonic() < deadline:
            snap = _get_json(url + "/journal")
            if snap["ledger"]["pending"] == 0:
                break
            time.sleep(0.2)
        recovery_ms = (time.monotonic() - t0) * 1000.0
        ledger = snap["ledger"]
        replayed = snap["replayed"]
        matches, mismatches = 0, 0
        for rid, rec in replayed.items():
            frame = frames_by_rid.get(rid)
            if frame is None:
                continue
            code, resp, _ = _post(url, _body(frame))
            if code == 200 and _response_sha(resp) == rec["sha256"]:
                matches += 1
            else:
                mismatches += 1
        return {
            "pending_at_takeover": disk["pending"],
            "min_pending_required": min_pending,
            "acked": ledger["appended"],
            "acked_loss": ledger["pending"],
            "replayed": ledger["replayed"],
            "done_before_kill": disk["done"],
            "cancelled": ledger["cancelled"],
            "recovery_warm_ms": round(recovery_ms, 1),
            "replay_verified": matches,
            "replay_mismatched": mismatches,
            "replay_bit_identical": bool(
                matches >= 1 and mismatches == 0
            ),
        }
    finally:
        _reap(proc)
        shutil.rmtree(trace_b, ignore_errors=True)


def _arm_kill_midburst(a_path, ap_path, size):
    """SIGKILL mid-burst -> --takeover -> zero acked loss, replay
    bit-identity, recovery wall."""
    _, _, frames = _proxy_frames(size, 6)
    state_dir = tempfile.mkdtemp(prefix="ia_chaos_state_")
    trace_a = tempfile.mkdtemp(prefix="ia_chaos_victim_")
    proc, url = _spawn_serve(
        a_path, ap_path, trace_a, state_dir=state_dir
    )
    bodies = [(f"burst-{i}", _body(f)) for i, f in enumerate(frames)]
    frames_by_rid = {
        f"burst-{i}": f for i, f in enumerate(frames)
    }
    try:
        threads, _ = _burst(url, bodies)
        # Wait until every burst request is ACKED (journaled at
        # admission); the first dispatch is still compiling, so most
        # of the burst is queued when the kill lands.
        deadline = time.monotonic() + 120
        appended = 0
        while time.monotonic() < deadline:
            appended = _get_json(url + "/journal")["ledger"]["appended"]
            if appended >= len(frames):
                break
            time.sleep(0.05)
    finally:
        proc.kill()  # SIGKILL: no drain, no flush, no goodbye
        _reap(proc)
    for t in threads:
        t.join(timeout=30)
    arm = _takeover_and_verify(
        a_path, ap_path, state_dir, frames_by_rid, min_pending=4
    )
    arm.update({
        "name": "kill_midburst_takeover",
        "burst_size": len(frames),
        "acked_before_kill": appended,
    })
    shutil.rmtree(state_dir, ignore_errors=True)
    shutil.rmtree(trace_a, ignore_errors=True)
    return arm


def _arm_lattice_shape_burst(a_path, ap_path, size):
    """Round 20: kill mid-burst with RANDOM-SHAPED frames under
    `--lattice` — every frame a different (H, W), straddling bucket
    boundaries, one below the bottom rung and one over the top (the
    bypass path).  The journal stores RAW frames, so the `--takeover`
    successor (same spec) re-buckets each replay at admission; zero
    acked loss and bit-identical replay must hold exactly as they do
    for fixed-shape traffic."""
    import numpy as np

    spec = f"8:{size}:2"
    rng = np.random.default_rng(2016)
    shapes = []
    while len(shapes) < 5:
        hw = (int(rng.integers(5, size + 1)),
              int(rng.integers(5, size + 1)))
        if hw not in shapes:
            shapes.append(hw)
    shapes.append((size + 1, size))  # over the top rung: bypass path
    frames = [
        rng.random((h, w, 3)).astype(np.float32) for h, w in shapes
    ]
    state_dir = tempfile.mkdtemp(prefix="ia_chaos_lat_state_")
    trace_a = tempfile.mkdtemp(prefix="ia_chaos_lat_victim_")
    proc, url = _spawn_serve(
        a_path, ap_path, trace_a, state_dir=state_dir,
        extra=("--lattice", spec),
    )
    bodies = [(f"lat-{i}", _body(f)) for i, f in enumerate(frames)]
    frames_by_rid = {f"lat-{i}": f for i, f in enumerate(frames)}
    try:
        threads, _ = _burst(url, bodies)
        deadline = time.monotonic() + 120
        appended = 0
        while time.monotonic() < deadline:
            appended = _get_json(url + "/journal")["ledger"]["appended"]
            if appended >= len(frames):
                break
            time.sleep(0.05)
    finally:
        proc.kill()
        _reap(proc)
    for t in threads:
        t.join(timeout=30)
    arm = _takeover_and_verify(
        a_path, ap_path, state_dir, frames_by_rid, min_pending=4,
        extra=("--lattice", spec),
    )
    arm.update({
        "name": "lattice_shape_burst",
        "lattice_spec": spec,
        "burst_size": len(frames),
        "burst_shapes": [list(s) for s in shapes],
        "acked_before_kill": appended,
    })
    shutil.rmtree(state_dir, ignore_errors=True)
    shutil.rmtree(trace_a, ignore_errors=True)
    return arm


def _session_body(frame, session_id: str) -> bytes:
    import numpy as np

    return json.dumps({
        "image_b64": base64.b64encode(
            np.ascontiguousarray(frame.astype(np.float32)).tobytes()
        ).decode(),
        "shape": list(frame.shape),
        "dtype": "float32",
        "session_id": session_id,
    }).encode()


def arm_replica_kill_midburst(a_path, ap_path, size):
    """Round 21 fleet arm: SIGKILL one replica of a ROUTED fleet under
    a live burst, then roll the fleet through the full recovery story:

      1. a reference daemon serves a 3-frame session (the no-migration
         shas) and seeds the shared warm dir;
      2. replicas A + B come up on per-replica state dirs over the
         SHARED warm tier, fronted by an in-process FleetRouter; a
         video session pins to A;
      3. B is SIGKILLed with acked (journaled) requests still queued —
         the router's in-flight proxies to B retry on A, so every
         live client still gets a 200;
      4. a --takeover successor B2 replays B's pending entries with
         zero acked loss and bit-identical outputs, then joins the
         router;
      5. A drains THROUGH the router: its drain snapshot (sessions
         before journal compaction — the round-21 ordering fix) lands,
         the router migrates A's pinned session to B2 via
         /sessions/adopt, and the session's NEXT frame — served by B2
         — must be bit-identical to the reference daemon's frame 3.

    Returns the arm dict ROUTER_r21.json embeds (check_router gates
    acked_loss == 0, replay + migrated-frame bit-identity, and at
    least one migrated session)."""
    import numpy as np

    from image_analogies_tpu.serving.journal import (
        RequestJournal, journal_path,
    )
    from image_analogies_tpu.serving.router import FleetRouter
    from image_analogies_tpu.telemetry.metrics import MetricsRegistry

    rng = np.random.default_rng(2116)
    sess_frames = [
        rng.random((size, size, 3)).astype(np.float32)
        for _ in range(3)
    ]
    burst_frames = [
        rng.random((size, size, 3)).astype(np.float32)
        for _ in range(4)
    ]
    # The direct backlog uses a shape the shared warm tier has NOT
    # seen: B's first one stalls on a real XLA compile, so the kill
    # reliably lands with acked-but-unserved entries queued behind it
    # (warm-shape frames drain faster than a poll can observe).
    backlog_frames = [
        rng.random((size + 8, size + 8, 3)).astype(np.float32)
        for _ in range(6)
    ]
    warm = tempfile.mkdtemp(prefix="ia_fleet_warm_")
    sa = tempfile.mkdtemp(prefix="ia_fleet_sa_")
    sb = tempfile.mkdtemp(prefix="ia_fleet_sb_")
    traces = [
        tempfile.mkdtemp(prefix=f"ia_fleet_t{i}_") for i in range(4)
    ]
    warm_extra = ("--warm-dir", warm)
    # Fleet replicas take a direct backlog ON TOP of routed spillover;
    # a deeper admission queue keeps back-pressure 429s out of the
    # zero-acked-loss measurement (last --max-queue-depth wins).
    fleet_extra = warm_extra + ("--max-queue-depth", "32")
    arm = {"name": "replica_kill_midburst", "burst_size":
           len(burst_frames) + len(backlog_frames),
           "shared_warm_dir": True}
    router = None
    pa = pb = pb2 = None
    try:
        # 1. Reference session run (also seeds the shared warm tier).
        ref_proc, ref_url = _spawn_serve(
            a_path, ap_path, traces[0], extra=warm_extra
        )
        ref_shas = []
        try:
            for f in sess_frames:
                code, resp, _ = _post(
                    ref_url, _session_body(f, "s-mig")
                )
                if code != 200:
                    raise RuntimeError(
                        f"reference session frame failed: {code}"
                    )
                ref_shas.append(_response_sha(resp))
        finally:
            _reap(ref_proc)
        # 2. Fleet: A first (the session pins to it while it is the
        # only replica), then B, behind the router.
        pa, ua = _spawn_serve(
            a_path, ap_path, traces[1], state_dir=sa, extra=fleet_extra
        )
        router = FleetRouter(
            MetricsRegistry(), poll_interval_s=0.2
        ).start()
        router.add_replica(ua, name="ra")
        pinned_to = None
        for f in sess_frames[:2]:
            code, resp, hdrs = _post(
                router.url, _session_body(f, "s-mig")
            )
            if code != 200:
                raise RuntimeError(
                    f"session frame via router failed: {code}"
                )
            pinned_to = hdrs.get("X-Routed-To")
        arm["session_pinned_to"] = pinned_to
        pb, ub = _spawn_serve(
            a_path, ap_path, traces[2], state_dir=sb, extra=fleet_extra
        )
        router.add_replica(ub, name="rb")
        # 3. Live burst through the router PLUS a direct backlog on B
        # (max_batch 1 serializes it), so the kill lands with acked-
        # but-unserved entries in B's journal.
        frames_by_rid = {
            f"fleet-{i}": f
            for i, f in enumerate(burst_frames + backlog_frames)
        }
        routed = [(f"fleet-{i}", _body(f))
                  for i, f in enumerate(burst_frames)]
        direct = [(f"fleet-{i + 4}", _body(f))
                  for i, f in enumerate(backlog_frames)]
        threads_r, results_r = _burst(router.url, routed)
        threads_d, _ = _burst(ub, direct)
        deadline = time.monotonic() + 60
        pending_seen = 0
        while time.monotonic() < deadline:
            ledger = _get_json(ub + "/journal")["ledger"]
            pending_seen = ledger["pending"]
            if ledger["appended"] >= 3 and pending_seen >= 2:
                break
            time.sleep(0.02)
        arm["pending_seen_at_kill"] = pending_seen
        pb.kill()  # SIGKILL: no drain, no snapshot, no goodbye
        _reap(pb)
        for t in threads_r + threads_d:
            t.join(timeout=300)
        # Every ROUTED request must have been served (B's failures
        # retried on A); direct-to-B clients legitimately see resets.
        arm["routed_burst"] = len(routed)
        arm["routed_served"] = sum(
            1 for r in results_r if r is not None and r[0] == 200
        )
        arm["router_retries"] = router.retries
        disk = RequestJournal(journal_path(sb)).counts()
        arm["pending_at_takeover"] = disk["pending"]
        # 4. Takeover successor B2 replays B's pending set.
        t0 = time.monotonic()
        pb2, ub2 = _spawn_serve(
            a_path, ap_path, traces[3], takeover=sb, extra=fleet_extra
        )
        snap = None
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            snap = _get_json(ub2 + "/journal")
            if snap["ledger"]["pending"] == 0:
                break
            time.sleep(0.2)
        arm["recovery_warm_ms"] = round(
            (time.monotonic() - t0) * 1000.0, 1
        )
        ledger = snap["ledger"]
        matches, mismatches = 0, 0
        for rid, rec in snap["replayed"].items():
            frame = frames_by_rid.get(rid)
            if frame is None:
                continue
            code, resp, _ = _post(ub2, _body(frame))
            if code == 200 and _response_sha(resp) == rec["sha256"]:
                matches += 1
            else:
                mismatches += 1
        arm.update({
            "acked": ledger["appended"],
            "acked_loss": ledger["pending"],
            "replayed": ledger["replayed"],
            "replay_verified": matches,
            "replay_mismatched": mismatches,
            "replay_bit_identical": bool(
                matches >= 1 and mismatches == 0
            ),
        })
        router.add_replica(ub2, name="rb2")
        # 5. Graceful drain of A through the router: snapshot lands
        # (sessions before journal compaction), session migrates to
        # B2, and the migrated stream's next frame is bit-identical.
        report = router.drain_replica("ra", wait_s=180)
        arm["drain_report"] = {
            "drained": report["drained"],
            "sessions_migrated": report["sessions_migrated"],
            "migrated_to": report.get("migrated_to"),
        }
        arm["sessions_migrated"] = len(report["sessions_migrated"])
        try:
            pa.wait(timeout=120)
        except subprocess.TimeoutExpired:
            pass
        code, resp, hdrs = _post(
            router.url, _session_body(sess_frames[2], "s-mig")
        )
        arm["migrated_frame_routed_to"] = hdrs.get("X-Routed-To")
        arm["migrated_frame_bit_identical"] = bool(
            code == 200 and _response_sha(resp) == ref_shas[2]
        )
        return arm
    finally:
        if router is not None:
            router.stop()
        for p in (pa, pb, pb2):
            if p is not None:
                _reap(p)
        for d in (warm, sa, sb, *traces):
            shutil.rmtree(d, ignore_errors=True)


def _arm_serve_crash_torn(a_path, ap_path, size):
    """IA_FAULT_PLAN=serve_crash kills the daemon between journal
    append and ack; a torn half-line is appended on top; the takeover
    must replay the completed lines and skip the torn tail."""
    _, _, frames = _proxy_frames(size, 3)
    state_dir = tempfile.mkdtemp(prefix="ia_chaos_crash_")
    trace_a = tempfile.mkdtemp(prefix="ia_chaos_crashv_")
    # Append ordinal 2 == the third admitted request: the daemon
    # os._exit(137)s after journaling it, before ack or dispatch.
    proc, url = _spawn_serve(
        a_path, ap_path, trace_a, state_dir=state_dir,
        env_extra={"IA_FAULT_PLAN": "serve_crash:2:fail"},
    )
    frames_by_rid = {
        f"crash-{i}": f for i, f in enumerate(frames)
    }
    crash_rc = None
    try:
        bodies = [
            (f"crash-{i}", _body(f)) for i, f in enumerate(frames)
        ]
        threads, _ = _burst(url, bodies)
        for t in threads:
            t.join(timeout=300)
        proc.wait(timeout=60)
        crash_rc = proc.returncode
    finally:
        _reap(proc)
    # Torn trailing line: a crash mid-write loses at most the torn
    # tail; replay must skip it and keep every completed line.
    with open(os.path.join(state_dir, "journal.jsonl"), "ab") as f:
        f.write(b'{"kind":"req","request_id":"torn-tail","mani')
    arm = _takeover_and_verify(
        a_path, ap_path, state_dir, frames_by_rid, min_pending=1
    )
    arm.update({
        "name": "serve_crash_torn",
        "crash_exit_code": crash_rc,
        "torn_line_appended": True,
    })
    shutil.rmtree(state_dir, ignore_errors=True)
    shutil.rmtree(trace_a, ignore_errors=True)
    return arm


def _arm_archive_torn(a_path, ap_path, size):
    """Round 23: IA_FAULT_PLAN=archive_crash hard-exits the daemon
    with half an archive snapshot line on disk; a restart with the
    same --archive-dir must reload cleanly (torn tail skipped and
    COUNTED), resume the pre-crash anomaly baseline, and stamp its
    windows with a strictly later observatory generation.  Reused by
    tools/archive_drill.py for ARCHIVE_r23.json's torn cell."""
    _, _, frames = _proxy_frames(size, 1)
    state_dir = tempfile.mkdtemp(prefix="ia_chaos_archt_")
    arch_dir = tempfile.mkdtemp(prefix="ia_chaos_archd_")
    trace_a = tempfile.mkdtemp(prefix="ia_chaos_archv_")
    trace_b = tempfile.mkdtemp(prefix="ia_chaos_archw_")
    base_path = os.path.join(state_dir, "baseline.json")
    with open(base_path, "w") as f:
        json.dump({"pipeline": {"p99_warm_ms": 50.0}}, f)
    archive_flags = [
        "--archive-dir", arch_dir,
        "--archive-interval-s", "0.2", "--obs-interval-s", "0.2",
    ]
    # Archive write ordinal 3: past the boot record (seq 0) and at
    # least two whole snapshots, so the torn tail lands on a snapshot
    # that already has durable predecessors carrying the baseline.
    proc, _url = _spawn_serve(
        a_path, ap_path, trace_a, state_dir=state_dir,
        extra=[*archive_flags, "--baseline", base_path],
        env_extra={"IA_FAULT_PLAN": "archive_crash:3:fail"},
    )
    arm = {"name": "archive_torn_reload", "torn_line_appended": True}
    proc2 = None
    try:
        proc.wait(timeout=180)
        arm["crash_exit_code"] = proc.returncode
    except subprocess.TimeoutExpired:
        arm["crash_exit_code"] = None
    finally:
        _reap(proc)
    # Belt and braces on top of the fault's own half-line: a second
    # torn fragment with no newline, as a crash AFTER the buffered
    # write but before the next would leave.
    with open(os.path.join(arch_dir, "archive.jsonl"), "ab") as f:
        f.write(b'{"kind":"snapshot","boot_id":"torn-')
    try:
        proc2, url2 = _spawn_serve(
            a_path, ap_path, trace_b, state_dir=state_dir,
            extra=archive_flags,  # NO --baseline: must come from disk
        )
        snap = _get_json(url2 + "/archive")
        resumed = snap.get("resumed") or {}
        arm.update({
            "reload_clean": bool(resumed.get("records", 0) >= 2),
            "skipped_lines": resumed.get("skipped_lines"),
            "boots_before_restart": resumed.get("boots"),
            "baseline_resumed": bool(
                snap.get("anomaly_baseline_p99_ms") == 50.0
            ),
            "resumed_generation": resumed.get("generation"),
            "obs_generation": snap.get("obs_generation"),
            "generation_monotonic": bool(
                isinstance(resumed.get("generation"), int)
                and isinstance(snap.get("obs_generation"), int)
                and snap["obs_generation"] > resumed["generation"]
            ),
        })
        code, _resp, _ = _post(url2, _body(frames[0]))
        arm["post_restart_request_ok"] = bool(code == 200)
    finally:
        if proc2 is not None:
            _reap(proc2)
        for d in (state_dir, arch_dir, trace_a, trace_b):
            shutil.rmtree(d, ignore_errors=True)
    return arm


def _arm_drain_handoff(a_path, ap_path, size):
    """POST /drain with a request in flight: in-flight 200 delivered,
    new request 503 + Retry-After, exit 0, flight reason drain."""
    _, _, frames = _proxy_frames(size, 2)
    state_dir = tempfile.mkdtemp(prefix="ia_chaos_drain_")
    trace = tempfile.mkdtemp(prefix="ia_chaos_drainv_")
    proc, url = _spawn_serve(
        a_path, ap_path, trace, state_dir=state_dir,
        extra=["--drain-deadline-s", "120"],
    )
    inflight_result = {}

    def inflight_worker():
        try:
            inflight_result["r"] = _post(url, _body(frames[0]))
        except Exception as e:  # noqa: BLE001
            inflight_result["err"] = str(e)

    arm = {"name": "drain_handoff"}
    try:
        t = threading.Thread(target=inflight_worker)
        t.start()
        time.sleep(0.5)  # the request is compiling in its dispatch
        req = urllib.request.Request(
            url + "/drain", data=b"{}", method="POST"
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            arm["drain_status"] = resp.status
        code, resp_new, hdrs = _post(url, _body(frames[1]))
        arm["new_request_status"] = code
        arm["new_request_503"] = bool(
            code == 503 and resp_new.get("status") == "unavailable"
        )
        arm["retry_after_present"] = "Retry-After" in hdrs
        t.join(timeout=300)
        code_in, resp_in, _ = inflight_result.get("r", (None, {}, {}))
        arm["inflight_delivered"] = bool(code_in == 200)
        proc.wait(timeout=180)
        arm["exit_code"] = proc.returncode
    finally:
        _reap(proc)
    flight_path = os.path.join(trace, "flight.json")
    arm["flight_reason"] = None
    if os.path.isfile(flight_path):
        with open(flight_path) as f:
            arm["flight_reason"] = json.load(f).get("flushed_on")
    arm["observed_warmup_written"] = os.path.isfile(
        os.path.join(state_dir, "warmup.observed.json")
    )
    with open(os.path.join(state_dir, "journal.jsonl")) as f:
        lines = [json.loads(ln) for ln in f if ln.strip()]
    marks = [r for r in lines if r.get("kind") == "mark"]
    arm["journal_done_marks"] = sum(
        1 for r in marks if r.get("outcome") == "done"
    )
    shutil.rmtree(state_dir, ignore_errors=True)
    shutil.rmtree(trace, ignore_errors=True)
    return arm


def _inprocess_arms(size: int):
    """The three fault-point arms that need no subprocess: diskfull
    (counted, not raised), hang (bounded by the dispatch deadline),
    evict (honest miss).  One shared jit compile."""
    import numpy as np

    from image_analogies_tpu.config import SynthConfig
    from image_analogies_tpu.runtime.faults import set_fault_plan
    from image_analogies_tpu.serving.daemon import SynthDaemon
    from image_analogies_tpu.telemetry.metrics import (
        MetricsRegistry,
        set_registry,
    )

    a, ap, frames = _proxy_frames(size, 2)
    cfg = SynthConfig(
        levels=2, matcher="patchmatch", pallas_mode="off",
        em_iters=1, pm_iters=2,
    )
    arms = []

    def run_arm(name, plan, fn, **daemon_kw):
        reg = MetricsRegistry()
        prev = set_registry(reg)
        state = tempfile.mkdtemp(prefix=f"ia_chaos_{name}_")
        daemon = SynthDaemon(
            a, ap, cfg, registry=reg, max_batch=1, max_wait_ms=5.0,
            max_queue_depth=8, observability=False,
            state_dir=state, **daemon_kw,
        ).start()
        set_fault_plan(plan)
        try:
            arm = fn(daemon)
        finally:
            set_fault_plan(None)
            daemon.stop()
            set_registry(prev)
            shutil.rmtree(state, ignore_errors=True)
        arm["name"] = name
        arm["fault_plan"] = plan
        arms.append(arm)

    def diskfull(daemon):
        # Write ordinal 0 == the first request's journal append: the
        # line never hits disk, the error is counted, the request
        # still serves.
        code, resp, _ = _post(daemon.url, _body(frames[0]))
        counts = daemon.journal.counts()
        return {
            "response_ok": bool(code == 200),
            "errors_counted": counts["errors"],
            "ledger_appended": counts["appended"],
        }

    run_arm("serve_diskfull", "serve_diskfull:0:fail", diskfull)

    def hang(daemon):
        t0 = time.monotonic()
        code1, _, _ = _post(daemon.url, _body(frames[0]))
        bounded_s = time.monotonic() - t0
        set_fault_plan(None)
        code2, _, _ = _post(daemon.url, _body(frames[0]))
        return {
            "hung_request_status": code1,
            "bounded_wall_s": round(bounded_s, 2),
            # The injected hang asks for 60 s; the dispatch deadline
            # aborts it in ~2.  15 s of slack absorbs CI noise while
            # still proving the bound did the work.
            "bounded": bool(bounded_s < 15.0),
            "survived": bool(code2 == 200),
        }

    run_arm(
        "serve_hang", "serve_hang:0:hang:60", hang,
        dispatch_deadline_s=2.0,
    )

    def evict(daemon):
        code1, r1, _ = _post(daemon.url, _body(frames[0]))
        code2, r2, _ = _post(daemon.url, _body(frames[0]))
        # Dispatch ordinal 2 == the third client dispatch: the forced
        # epoch eviction lands before its cache lookup.
        set_fault_plan("serve_evict:2:fail")
        code3, r3, _ = _post(daemon.url, _body(frames[0]))
        return {
            "warm_cache": r2.get("cache"),
            "post_evict_cache": r3.get("cache"),
            "honest_miss": bool(
                r2.get("cache") == "hit" and r3.get("cache") != "hit"
            ),
            "response_ok": bool(
                code1 == 200 and code2 == 200 and code3 == 200
            ),
            "evictions": daemon.cache.snapshot().get("evictions"),
        }

    run_arm("serve_evict", None, evict)
    return arms


def run_chaos_serve(size: int = 24):
    import numpy as np

    from image_analogies_tpu.utils.io import save_image

    a, ap, _ = _proxy_frames(size, 0)
    asset_dir = tempfile.mkdtemp(prefix="ia_chaos_assets_")
    a_path = os.path.join(asset_dir, "a.png")
    ap_path = os.path.join(asset_dir, "ap.png")
    save_image(a_path, a)
    save_image(ap_path, ap)

    arms = []
    try:
        arms.extend(_inprocess_arms(size))
        arms.append(_arm_drain_handoff(a_path, ap_path, size))
        arms.append(_arm_kill_midburst(a_path, ap_path, size))
        arms.append(_arm_serve_crash_torn(a_path, ap_path, size))
        arms.append(_arm_lattice_shape_burst(a_path, ap_path, size))
        # Round 23: telemetry-archive SIGKILL-mid-append arm.  Not a
        # headline cell (the committed CHAOS_SERVE_r16.json predates
        # it; the validator checks required arms by name and ignores
        # extras) — ARCHIVE_r23.json carries its acceptance floor.
        arms.append(_arm_archive_torn(a_path, ap_path, size))
    finally:
        shutil.rmtree(asset_dir, ignore_errors=True)

    by_name = {arm["name"]: arm for arm in arms}
    kill = by_name["kill_midburst_takeover"]
    torn = by_name["serve_crash_torn"]
    # Round 20 randomized-shape arm: folded into the headline cells so
    # the resilience claims cover bucket-boundary replay too.  (The
    # committed CHAOS_SERVE_r16.json predates the arm; its validator
    # checks it only when present.)
    lat = by_name.get("lattice_shape_burst")
    recovery_arms = [a for a in (kill, torn, lat) if a is not None]
    return {
        "schema_version": CHAOS_SERVE_SCHEMA_VERSION,
        "kind": "chaos_serve",
        "round": 16,
        "generated_by": "tools/chaos_serve.py",
        "proxy_size": size,
        "config": {
            "levels": 2, "matcher": "patchmatch", "em_iters": 1,
            "pm_iters": 2, "max_batch": 1,
        },
        # Headline cells tools/check_trajectory.py tracks across
        # rounds (replay_bit_identical as 1.0/0.0 so the numeric
        # series machinery can hold its floor at 1.0).
        "acked_loss": max(
            a["acked_loss"] for a in recovery_arms
        ),
        "recovery_warm_ms": kill["recovery_warm_ms"],
        "replay_bit_identical": float(all(
            a["replay_bit_identical"] for a in recovery_arms
        )),
        "arms": arms,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="CHAOS_SERVE_r16.json")
    ap.add_argument("--size", type=int, default=24)
    args = ap.parse_args(argv)
    record = run_chaos_serve(args.size)
    with open(args.out, "w") as f:
        json.dump(record, f, indent=1)
        f.write("\n")
    for arm in record["arms"]:
        keys = [
            k for k in (
                "acked_loss", "replay_bit_identical", "exit_code",
                "response_ok", "bounded", "survived", "honest_miss",
                "inflight_delivered", "new_request_503",
                "reload_clean", "baseline_resumed",
                "generation_monotonic",
            ) if k in arm
        ]
        print(
            f"{arm['name']:>24}: "
            + ", ".join(f"{k}={arm[k]}" for k in keys)
        )
    print(
        f"wrote {args.out} (acked_loss={record['acked_loss']}, "
        f"recovery_warm_ms={record['recovery_warm_ms']}, "
        f"bit_identical={record['replay_bit_identical']})"
    )
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from check_chaos_serve import validate_chaos_serve

    errs = validate_chaos_serve(record)
    for e in errs:
        print(f"chaos_serve: VIOLATION: {e}", file=sys.stderr)
    return 1 if errs else 0


if __name__ == "__main__":
    sys.exit(main())
