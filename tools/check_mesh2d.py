#!/usr/bin/env python
"""Validate a MESH2D_r17.json 2-D-mesh scale artifact (round 17).

The 2-D scale story mixes measured rows (what the box could run) with
modeled rows (the 8192^2/16384^2/32768^2 projections the box cannot).
This validator is what keeps that mix honest:

- **Measured rows** must carry a positive warm wall, the planner
  verdict that chose their mesh, and `bit_identical_to_1d: true` —
  the numerics contract the 2-D tests pin.  A measured row that lost
  bit-identity is not a scale result, it is a miscompile report.
- **Modeled rows** are RE-PRICED from their recorded inputs: the
  planner is re-run on `model_inputs` (shapes, cfg knobs, HBM budget)
  and every cell — mesh_shape, comms_bytes, dma_bytes,
  residency_bytes, and the bandwidth-priced wall — must match what
  the current models produce.  A hand-edited projection, or a model
  change that silently re-prices committed cells, fails loudly here.
- Rows for the headline scale sizes (8192 and 16384) must exist; a
  modeled row may later be REPLACED by a measured one (real metal),
  never merely reworded.

Usage:
    python tools/check_mesh2d.py MESH2D_r17.json

Runs under pytest too (tests/test_mesh2d.py validates the COMMITTED
artifact) so tier-1 fails if the record is missing, truncated, or
structurally degraded.  Exit codes: 0 valid, 1 violations, 2
unreadable input.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

MESH2D_SCHEMA_VERSION = 1
PROVENANCES = ("measured", "modeled")
REQUIRED_SIZES = (8192, 16384)
_WALL_REL_TOL = 1e-3


def _num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _pos_int(v) -> bool:
    return isinstance(v, int) and not isinstance(v, bool) and v > 0


def _check_modeled(i: int, row: dict, n_devices: int) -> List[str]:
    """Re-price a modeled row from its recorded inputs."""
    errs: List[str] = []
    where = f"rows[{i}] (size {row.get('size')})"
    mi = row.get("model_inputs")
    bw = row.get("model_bandwidths")
    if not isinstance(mi, dict) or not isinstance(bw, dict):
        return [f"{where}: modeled row lacks model_inputs/"
                "model_bandwidths — an unpriceable projection"]
    if not isinstance(row.get("basis"), str) or not row["basis"]:
        errs.append(f"{where}: modeled row lacks its basis statement")
    hbm_bps, ici_bps = bw.get("hbm_Bps"), bw.get("ici_Bps")
    if not (_num(hbm_bps) and hbm_bps > 0 and _num(ici_bps)
            and ici_bps > 0):
        return errs + [f"{where}: model_bandwidths not positive"]
    if mi.get("n_devices") != n_devices:
        errs.append(
            f"{where}: model_inputs.n_devices {mi.get('n_devices')!r} "
            f"!= artifact n_devices {n_devices}"
        )
    try:
        from image_analogies_tpu import SynthConfig
        from image_analogies_tpu.parallel.plan2d import plan_mesh_shape

        cfg = SynthConfig(**mi["cfg"])
        plan = plan_mesh_shape(
            mi["n_devices"], tuple(mi["a_shape"]), tuple(mi["b_shape"]),
            cfg, hbm_bytes=mi["hbm_bytes"],
        )
    except Exception as e:  # noqa: BLE001 — any re-price failure is a finding
        return errs + [f"{where}: model_inputs do not re-price: {e}"]
    c = plan.chosen
    if row.get("mesh_shape") != [plan.n_bands, plan.n_slabs]:
        errs.append(
            f"{where}: recorded mesh_shape {row.get('mesh_shape')} != "
            f"re-planned [{plan.n_bands}, {plan.n_slabs}]"
        )
    for field, want in (
        ("comms_bytes", c.comms_bytes),
        ("dma_bytes", c.dma_bytes),
        ("residency_bytes", c.residency_bytes),
    ):
        if row.get(field) != want:
            errs.append(
                f"{where}: recorded {field} {row.get(field)!r} != "
                f"re-priced {want} — the cell no longer matches the "
                "model that claims to have produced it"
            )
    want_wall = c.dma_bytes / hbm_bps + c.comms_bytes / ici_bps
    wall = row.get("wall_s")
    if not _num(wall) or abs(wall - want_wall) > max(
        _WALL_REL_TOL * want_wall, 1e-3
    ):
        errs.append(
            f"{where}: modeled wall_s {wall!r} != re-priced "
            f"{want_wall:.3f} at the stated bandwidths"
        )
    return errs


def validate_mesh2d(record: dict) -> List[str]:
    """Return a list of violations (empty = valid)."""
    errs: List[str] = []
    if not isinstance(record, dict):
        return ["record is not a JSON object"]
    if record.get("schema_version") != MESH2D_SCHEMA_VERSION:
        errs.append(
            f"schema_version {record.get('schema_version')!r} != "
            f"{MESH2D_SCHEMA_VERSION}"
        )
    if not isinstance(record.get("comment"), str) or not record["comment"]:
        errs.append("missing provenance comment")
    n_devices = record.get("n_devices")
    if not _pos_int(n_devices):
        errs.append(f"n_devices {n_devices!r} not a positive int")
        n_devices = 0
    rows = record.get("rows")
    if not isinstance(rows, list) or not rows:
        return errs + ["rows missing or empty"]
    last_size = 0
    seen = set()
    for i, row in enumerate(rows):
        if not isinstance(row, dict):
            errs.append(f"rows[{i}] is not an object")
            continue
        size = row.get("size")
        where = f"rows[{i}] (size {size})"
        if not _pos_int(size):
            errs.append(f"rows[{i}] size {size!r} not a positive int")
            continue
        seen.add(size)
        if size <= last_size:
            errs.append(f"{where}: size not strictly increasing")
        last_size = size
        prov = row.get("provenance")
        if prov not in PROVENANCES:
            errs.append(
                f"{where}: provenance {prov!r} names none of "
                f"{PROVENANCES}"
            )
            continue
        ms = row.get("mesh_shape")
        if (
            not isinstance(ms, list) or len(ms) != 2
            or not all(_pos_int(v) for v in ms)
            or (n_devices and ms[0] * ms[1] != n_devices)
        ):
            errs.append(
                f"{where}: mesh_shape {ms!r} is not a (bands, slabs) "
                f"factorization of {n_devices} devices"
            )
        plan = row.get("plan")
        if not isinstance(plan, dict) or "chosen" not in plan or \
                "source" not in plan:
            errs.append(
                f"{where}: planner verdict (plan.chosen/plan.source) "
                "not recorded — the decision is unauditable"
            )
        if prov == "measured":
            if not (_num(row.get("wall_s")) and row["wall_s"] > 0):
                errs.append(
                    f"{where}: measured wall_s {row.get('wall_s')!r} "
                    "not positive"
                )
            if row.get("bit_identical_to_1d") is not True:
                errs.append(
                    f"{where}: measured row without "
                    "bit_identical_to_1d: true — a 2-D run that "
                    "diverged from the 1-D runner is a miscompile "
                    "report, not a scale result"
                )
            if "model_inputs" in row or "basis" in row:
                errs.append(
                    f"{where}: measured row carries modeled-row "
                    "fields — provenance is ambiguous"
                )
        else:
            errs.extend(_check_modeled(i, row, n_devices))
    for size in REQUIRED_SIZES:
        if size not in seen:
            errs.append(
                f"no row for the headline scale size {size} — the "
                "un-cap claim has no cell backing it"
            )
    return errs


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("artifact", help="MESH2D_r*.json path")
    args = ap.parse_args(argv)
    try:
        with open(args.artifact) as f:
            record = json.load(f)
    except (OSError, ValueError) as e:
        print(f"check_mesh2d: cannot read {args.artifact}: {e}",
              file=sys.stderr)
        return 2
    errs = validate_mesh2d(record)
    if errs:
        for e in errs:
            print(f"check_mesh2d: {e}", file=sys.stderr)
        print(f"check_mesh2d: FAIL — {len(errs)} violation(s)",
              file=sys.stderr)
        return 1
    rows = record["rows"]
    n_meas = sum(1 for r in rows if r.get("provenance") == "measured")
    print(
        f"check_mesh2d: OK — {len(rows)} rows ({n_meas} measured, "
        f"{len(rows) - n_meas} modeled re-priced) on "
        f"{record['n_devices']} devices"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
