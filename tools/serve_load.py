#!/usr/bin/env python
"""Closed-loop load generator for the synthesis daemon (round 13).

Drives an in-process `SynthDaemon` (same code path as `ia-synth
serve`, minus the subprocess) through the serving acceptance
scenarios and writes one SERVE_r13.json artifact:

  1. cache probe — one cold request (compiles) and one warm repeat of
     the same shape (cache hit): `latency_delta_ms` is the measured
     compile saving, the tentpole's headline number;
  2. load sweep — for each client count, that many closed-loop
     clients each post `--requests-per-client` same-shape requests
     back-to-back (429s are recorded, not retried: the sweep measures
     the admission decision, not client patience).  The burst arm's
     client count deliberately exceeds the queue depth so admission
     control MUST shed — a sweep that never sheds fails validation;
  3. ledger + sentinel — the final admission ledger scraped from the
     daemon's registry, plus the sentinel serving check's verdict on
     the same metrics the daemon's /healthz serves.

The artifact is validated with tools/check_serve.py before this tool
exits 0 (the generator never commits a record its own validator
rejects).

Usage:
    python tools/serve_load.py --out SERVE_r13.json [--size 32]
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import statistics
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import List, Optional, Tuple

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from check_serve import validate_serve  # noqa: E402


def _post(url: str, body: bytes,
          timeout: float = 600.0) -> Tuple[int, dict]:
    req = urllib.request.Request(
        url + "/synthesize", data=body,
        headers={"Content-Type": "application/json"}, method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _quantiles(lat_ms: List[float]) -> Tuple[Optional[float],
                                             Optional[float]]:
    if not lat_ms:
        return None, None
    if len(lat_ms) == 1:
        return round(lat_ms[0], 3), round(lat_ms[0], 3)
    qs = statistics.quantiles(lat_ms, n=100, method="inclusive")
    return round(qs[49], 3), round(qs[98], 3)


def _counter_total(snap: dict, name: str) -> float:
    return float(sum(
        v for v in snap.get(name, {}).get("values", {}).values()
        if isinstance(v, (int, float))
    ))


def run_load(args) -> dict:
    import numpy as np

    from image_analogies_tpu.config import SynthConfig
    from image_analogies_tpu.serving.daemon import SynthDaemon
    from image_analogies_tpu.telemetry.metrics import (
        MetricsRegistry,
        set_registry,
    )

    rng = np.random.default_rng(args.seed)
    size = args.size
    a, ap, b = (
        rng.random((size, size, 3)).astype(np.float32) for _ in range(3)
    )
    cfg = SynthConfig(
        levels=2, matcher="patchmatch", pallas_mode="off",
        em_iters=1, pm_iters=2,
    )
    body = json.dumps({
        "image_b64": base64.b64encode(
            np.ascontiguousarray(b).tobytes()
        ).decode(),
        "shape": [size, size, 3],
        "dtype": "float32",
    }).encode()

    registry = MetricsRegistry()
    prev = set_registry(registry)
    daemon = SynthDaemon(
        a, ap, cfg, registry=registry,
        max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
        max_queue_depth=args.max_queue_depth,
        cache_capacity=4, max_retries=1,
    ).start()
    try:
        # -- 1. cache probe: cold (compiles) vs warm repeat shape.
        t0 = time.perf_counter()
        code, r = _post(daemon.url, body)
        cold_ms = (time.perf_counter() - t0) * 1000.0
        if code != 200 or r.get("cache") != "miss":
            raise RuntimeError(
                f"cold probe: expected 200/miss, got {code}/"
                f"{r.get('cache')!r} ({r.get('error')})"
            )
        t0 = time.perf_counter()
        code, r = _post(daemon.url, body)
        warm_ms = (time.perf_counter() - t0) * 1000.0
        if code != 200 or r.get("cache") != "hit":
            raise RuntimeError(
                f"warm probe: expected 200/hit, got {code}/"
                f"{r.get('cache')!r} ({r.get('error')})"
            )
        print(
            f"serve_load: cache probe cold={cold_ms:.0f} ms "
            f"warm={warm_ms:.0f} ms "
            f"(saved {cold_ms - warm_ms:.0f} ms)", flush=True,
        )

        # -- 2. closed-loop sweep.
        sweep = []
        for clients in args.clients:
            lock = threading.Lock()
            lat_ms: List[float] = []
            counts = {"completed": 0, "shed": 0, "failed": 0,
                      "hits": 0}
            barrier = threading.Barrier(clients)

            def client():
                barrier.wait()
                for _ in range(args.requests_per_client):
                    t0 = time.perf_counter()
                    code, r = _post(daemon.url, body)
                    wall = (time.perf_counter() - t0) * 1000.0
                    with lock:
                        if code == 200:
                            counts["completed"] += 1
                            lat_ms.append(wall)
                            if r.get("cache") == "hit":
                                counts["hits"] += 1
                        elif code == 429:
                            counts["shed"] += 1
                        else:
                            counts["failed"] += 1

            threads = [threading.Thread(target=client)
                       for _ in range(clients)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            p50, p99 = _quantiles(lat_ms)
            point = {
                "clients": clients,
                "requests": clients * args.requests_per_client,
                "completed": counts["completed"],
                "shed": counts["shed"],
                "failed": counts["failed"],
                "hit_ratio": round(
                    counts["hits"] / counts["completed"], 3
                ) if counts["completed"] else 0.0,
                "p50_ms": p50,
                "p99_ms": p99,
            }
            sweep.append(point)
            print(f"serve_load: sweep {point}", flush=True)

        # -- 3. final ledger + the sentinel's own verdict.
        snap = registry.to_dict()
        ledger = {
            k: _counter_total(snap, f"ia_serve_{k}_total")
            for k in ("requests", "admitted", "completed", "failed",
                      "shed")
        }
        health = daemon.health()
        serving_check = next(
            c["status"] for c in health["checks"]
            if c["name"] == "serving"
        )
        cache_snap = daemon.cache.snapshot()
        record = {
            "schema_version": 1,
            "kind": "serve",
            "round": 13,
            "proxy_size": size,
            "config": {
                "levels": cfg.levels, "matcher": cfg.matcher,
                "em_iters": cfg.em_iters, "pm_iters": cfg.pm_iters,
                "max_batch": daemon.policy.max_batch,
                "max_wait_ms": daemon.policy.max_wait_ms,
                "max_queue_depth": daemon.admission.max_depth,
                "requests_per_client": args.requests_per_client,
            },
            "cache": {
                "cold_ms": round(cold_ms, 3),
                "warm_ms": round(warm_ms, 3),
                "latency_delta_ms": round(cold_ms - warm_ms, 3),
                "hits": _counter_total(
                    snap, "ia_serve_excache_hits_total"
                ),
                "misses": _counter_total(
                    snap, "ia_serve_excache_misses_total"
                ),
                "evictions": cache_snap["evictions"],
                "resident": cache_snap["resident"],
            },
            "sweep": sweep,
            "ledger": ledger,
            "serving_check": serving_check,
        }
        return record
    finally:
        daemon.stop()
        set_registry(prev)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", required=True,
                    help="where to write SERVE_r13.json")
    ap.add_argument("--size", type=int, default=32,
                    help="proxy image edge (default 32)")
    ap.add_argument("--max-batch", type=int, default=2)
    ap.add_argument("--max-wait-ms", type=float, default=10.0)
    ap.add_argument("--max-queue-depth", type=int, default=3,
                    help="kept BELOW the burst client count so the "
                    "overload arm must shed")
    ap.add_argument("--clients", default="1,2,8",
                    help="comma-separated closed-loop client counts")
    ap.add_argument("--requests-per-client", type=int, default=3)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    args.clients = [int(c) for c in str(args.clients).split(",")]
    if max(args.clients) <= args.max_queue_depth:
        print(
            "serve_load: largest client count must exceed "
            f"--max-queue-depth ({args.max_queue_depth}) or the "
            "overload arm cannot shed"
        )
        return 1

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    record = run_load(args)
    errs = validate_serve(record)
    if errs:
        print("serve_load: generated record INVALID:")
        for e in errs:
            print(f"  - {e}")
        return 1
    tmp = args.out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(record, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, args.out)
    print(
        f"serve_load: wrote {args.out} (compile saved "
        f"{record['cache']['latency_delta_ms']} ms; ledger "
        f"{record['ledger']})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
