#!/usr/bin/env python
"""Closed-loop load generator for the synthesis daemon (round 13).

Drives an in-process `SynthDaemon` (same code path as `ia-synth
serve`, minus the subprocess) through the serving acceptance
scenarios and writes one SERVE_r13.json artifact:

  1. cache probe — one cold request (compiles) and one warm repeat of
     the same shape (cache hit): `latency_delta_ms` is the measured
     compile saving, the tentpole's headline number;
  2. load sweep — for each client count, that many closed-loop
     clients each post `--requests-per-client` same-shape requests
     back-to-back (429s are recorded, not retried: the sweep measures
     the admission decision, not client patience).  The burst arm's
     client count deliberately exceeds the queue depth so admission
     control MUST shed — a sweep that never sheds fails validation;
  3. ledger + sentinel — the final admission ledger scraped from the
     daemon's registry, plus the sentinel serving check's verdict on
     the same metrics the daemon's /healthz serves.

The artifact is validated with tools/check_serve.py before this tool
exits 0 (the generator never commits a record its own validator
rejects).

Round 15 adds `--slo-out SLO_r15.json`: the same run additionally
grades the DEFAULT_OBJECTIVES against the request-duration histogram
the daemon booked (telemetry/slo.py `evaluate_slo` — the exact
arithmetic the live `/slo` endpoint and the sentinel's check_slo
run), records a sample of the per-request ids every response echoed,
and reconstructs the warm probe's critical path from the daemon's
structured access log — validated with tools/check_slo.py (phase
attribution must sum within 5% of measured latency) before the write.

Usage:
    python tools/serve_load.py --out SERVE_r13.json [--size 32]
    python tools/serve_load.py --out /tmp/serve.json \\
        --slo-out SLO_r15.json
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import statistics
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import List, Optional, Tuple

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from check_serve import validate_serve  # noqa: E402
from check_slo import validate_slo  # noqa: E402


def _post(url: str, body: bytes, timeout: float = 600.0,
          headers: Optional[dict] = None) -> Tuple[int, dict]:
    h = {"Content-Type": "application/json"}
    if headers:
        h.update(headers)
    req = urllib.request.Request(
        url + "/synthesize", data=body, headers=h, method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _quantiles(lat_ms: List[float]) -> Tuple[Optional[float],
                                             Optional[float]]:
    if not lat_ms:
        return None, None
    if len(lat_ms) == 1:
        return round(lat_ms[0], 3), round(lat_ms[0], 3)
    qs = statistics.quantiles(lat_ms, n=100, method="inclusive")
    return round(qs[49], 3), round(qs[98], 3)


def _counter_total(snap: dict, name: str) -> float:
    return float(sum(
        v for v in snap.get(name, {}).get("values", {}).values()
        if isinstance(v, (int, float))
    ))


def run_load(args) -> dict:
    import numpy as np

    from image_analogies_tpu.config import SynthConfig
    from image_analogies_tpu.serving.daemon import SynthDaemon
    from image_analogies_tpu.telemetry.metrics import (
        MetricsRegistry,
        set_registry,
    )

    rng = np.random.default_rng(args.seed)
    size = args.size
    a, ap, b = (
        rng.random((size, size, 3)).astype(np.float32) for _ in range(3)
    )
    cfg = SynthConfig(
        levels=2, matcher="patchmatch", pallas_mode="off",
        em_iters=1, pm_iters=2,
    )
    body = json.dumps({
        "image_b64": base64.b64encode(
            np.ascontiguousarray(b).tobytes()
        ).decode(),
        "shape": [size, size, 3],
        "dtype": "float32",
    }).encode()

    registry = MetricsRegistry()
    prev = set_registry(registry)
    daemon = SynthDaemon(
        a, ap, cfg, registry=registry,
        max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
        max_queue_depth=args.max_queue_depth,
        cache_capacity=4, max_retries=1,
    ).start()
    request_ids: List[str] = []
    try:
        # -- 1. cache probe: cold (compiles) vs warm repeat shape.
        # The warm probe carries a CLIENT-CHOSEN X-Request-Id (round
        # 15): the echoed id + its access-log critical path prove the
        # request-scoped tracing flows end to end.
        t0 = time.perf_counter()
        code, r = _post(daemon.url, body)
        cold_ms = (time.perf_counter() - t0) * 1000.0
        if code != 200 or r.get("cache") != "miss":
            raise RuntimeError(
                f"cold probe: expected 200/miss, got {code}/"
                f"{r.get('cache')!r} ({r.get('error')})"
            )
        if r.get("request_id"):
            request_ids.append(r["request_id"])
        warm_rid = "slo-warm-probe"
        t0 = time.perf_counter()
        code, r = _post(daemon.url, body,
                        headers={"X-Request-Id": warm_rid})
        warm_ms = (time.perf_counter() - t0) * 1000.0
        if code != 200 or r.get("cache") != "hit":
            raise RuntimeError(
                f"warm probe: expected 200/hit, got {code}/"
                f"{r.get('cache')!r} ({r.get('error')})"
            )
        if r.get("request_id") != warm_rid:
            raise RuntimeError(
                f"warm probe: request_id {r.get('request_id')!r} != "
                f"supplied X-Request-Id {warm_rid!r}"
            )
        request_ids.append(warm_rid)
        print(
            f"serve_load: cache probe cold={cold_ms:.0f} ms "
            f"warm={warm_ms:.0f} ms "
            f"(saved {cold_ms - warm_ms:.0f} ms)", flush=True,
        )

        # -- 2. closed-loop sweep.
        sweep = []
        for clients in args.clients:
            lock = threading.Lock()
            lat_ms: List[float] = []
            counts = {"completed": 0, "shed": 0, "failed": 0,
                      "hits": 0}
            barrier = threading.Barrier(clients)

            def client():
                barrier.wait()
                for _ in range(args.requests_per_client):
                    t0 = time.perf_counter()
                    code, r = _post(daemon.url, body)
                    wall = (time.perf_counter() - t0) * 1000.0
                    with lock:
                        if code == 200:
                            counts["completed"] += 1
                            lat_ms.append(wall)
                            if r.get("cache") == "hit":
                                counts["hits"] += 1
                            if len(request_ids) < 8 and \
                                    r.get("request_id"):
                                request_ids.append(r["request_id"])
                        elif code == 429:
                            counts["shed"] += 1
                        else:
                            counts["failed"] += 1

            threads = [threading.Thread(target=client)
                       for _ in range(clients)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            p50, p99 = _quantiles(lat_ms)
            point = {
                "clients": clients,
                "requests": clients * args.requests_per_client,
                "completed": counts["completed"],
                "shed": counts["shed"],
                "failed": counts["failed"],
                "hit_ratio": round(
                    counts["hits"] / counts["completed"], 3
                ) if counts["completed"] else 0.0,
                "p50_ms": p50,
                "p99_ms": p99,
            }
            sweep.append(point)
            print(f"serve_load: sweep {point}", flush=True)

        # -- 3. final ledger + the sentinel's own verdict.
        snap = registry.to_dict()
        ledger = {
            k: _counter_total(snap, f"ia_serve_{k}_total")
            for k in ("requests", "admitted", "completed", "failed",
                      "shed")
        }
        health = daemon.health()
        serving_check = next(
            c["status"] for c in health["checks"]
            if c["name"] == "serving"
        )
        cache_snap = daemon.cache.snapshot()
        record = {
            "schema_version": 1,
            "kind": "serve",
            "round": 13,
            "proxy_size": size,
            "config": {
                "levels": cfg.levels, "matcher": cfg.matcher,
                "em_iters": cfg.em_iters, "pm_iters": cfg.pm_iters,
                "max_batch": daemon.policy.max_batch,
                "max_wait_ms": daemon.policy.max_wait_ms,
                "max_queue_depth": daemon.admission.max_depth,
                "requests_per_client": args.requests_per_client,
            },
            "cache": {
                "cold_ms": round(cold_ms, 3),
                "warm_ms": round(warm_ms, 3),
                "latency_delta_ms": round(cold_ms - warm_ms, 3),
                "hits": _counter_total(
                    snap, "ia_serve_excache_hits_total"
                ),
                "misses": _counter_total(
                    snap, "ia_serve_excache_misses_total"
                ),
                "evictions": cache_snap["evictions"],
                "resident": cache_snap["resident"],
            },
            "sweep": sweep,
            "ledger": ledger,
            "serving_check": serving_check,
        }

        # -- 4. SLO record (round 15, --slo-out): grade the default
        # objectives against the duration histogram the daemon booked
        # (the same arithmetic /slo serves), and reconstruct the warm
        # probe's critical path from the structured access log.
        slo_record = None
        if args.slo_out:
            from image_analogies_tpu.serving.accesslog import (
                find_request,
                phase_fields,
            )
            from image_analogies_tpu.telemetry.slo import evaluate_slo

            slo_report = evaluate_slo(snap)
            by_name = {o["name"]: o for o in slo_report["objectives"]}
            warm = by_name.get("warm_p99_latency_ms", {})
            access_rec = find_request(daemon.access.path, warm_rid)
            if access_rec is None:
                raise RuntimeError(
                    f"slo: warm probe {warm_rid!r} missing from "
                    f"access log {daemon.access.path}"
                )
            phases = dict(phase_fields(access_rec))
            total_ms = float(access_rec["total_ms"])
            attributed = sum(phases.values())
            slo_record = {
                "schema_version": 1,
                "kind": "slo",
                "round": 15,
                "proxy_size": size,
                "slo": slo_report,
                "p99_warm_ms": warm.get("observed_p99_ms"),
                "availability": by_name.get(
                    "availability", {}
                ).get("availability"),
                "request_ids": request_ids[:8],
                "critical_path": {
                    "request_id": warm_rid,
                    "total_ms": round(total_ms, 3),
                    "phases": {
                        k + "_ms": round(v, 3)
                        for k, v in phases.items()
                    },
                    "attributed_ms": round(attributed, 3),
                    "gap_pct": round(
                        100.0 * abs(total_ms - attributed) / total_ms, 3
                    ) if total_ms > 0 else None,
                },
            }
            print(
                f"serve_load: slo verdict {slo_report['verdict']!r} "
                f"(p99 warm {slo_record['p99_warm_ms']} ms, "
                f"availability {slo_record['availability']}, critical "
                f"path gap {slo_record['critical_path']['gap_pct']}%)",
                flush=True,
            )
        return record, slo_record
    finally:
        daemon.stop()
        set_registry(prev)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", required=True,
                    help="where to write SERVE_r13.json")
    ap.add_argument("--slo-out", default=None, metavar="PATH",
                    help="also write an SLO_r15.json SLO/critical-path "
                    "artifact from the same run (round 15)")
    ap.add_argument("--size", type=int, default=32,
                    help="proxy image edge (default 32)")
    ap.add_argument("--max-batch", type=int, default=2)
    ap.add_argument("--max-wait-ms", type=float, default=10.0)
    ap.add_argument("--max-queue-depth", type=int, default=3,
                    help="kept BELOW the burst client count so the "
                    "overload arm must shed")
    ap.add_argument("--clients", default="1,2,8",
                    help="comma-separated closed-loop client counts")
    ap.add_argument("--requests-per-client", type=int, default=3)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    args.clients = [int(c) for c in str(args.clients).split(",")]
    if max(args.clients) <= args.max_queue_depth:
        print(
            "serve_load: largest client count must exceed "
            f"--max-queue-depth ({args.max_queue_depth}) or the "
            "overload arm cannot shed"
        )
        return 1

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    record, slo_record = run_load(args)
    errs = validate_serve(record)
    if errs:
        print("serve_load: generated record INVALID:")
        for e in errs:
            print(f"  - {e}")
        return 1
    if args.slo_out:
        slo_errs = validate_slo(slo_record)
        if slo_errs:
            print("serve_load: generated SLO record INVALID:")
            for e in slo_errs:
                print(f"  - {e}")
            return 1
    _write_json(args.out, record)
    print(
        f"serve_load: wrote {args.out} (compile saved "
        f"{record['cache']['latency_delta_ms']} ms; ledger "
        f"{record['ledger']})"
    )
    if args.slo_out:
        _write_json(args.slo_out, slo_record)
        print(
            f"serve_load: wrote {args.slo_out} (verdict "
            f"{slo_record['slo']['verdict']!r})"
        )
    return 0


def _write_json(path: str, record: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(record, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


if __name__ == "__main__":
    sys.exit(main())
