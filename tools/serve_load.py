#!/usr/bin/env python
"""Closed-loop load generator for the synthesis daemon (round 13).

Drives an in-process `SynthDaemon` (same code path as `ia-synth
serve`, minus the subprocess) through the serving acceptance
scenarios and writes one SERVE_r13.json artifact:

  1. cache probe — one cold request (compiles) and one warm repeat of
     the same shape (cache hit): `latency_delta_ms` is the measured
     compile saving, the tentpole's headline number;
  2. load sweep — for each client count, that many closed-loop
     clients each post `--requests-per-client` same-shape requests
     back-to-back (429s are recorded, not retried: the sweep measures
     the admission decision, not client patience).  The burst arm's
     client count deliberately exceeds the queue depth so admission
     control MUST shed — a sweep that never sheds fails validation;
  3. ledger + sentinel — the final admission ledger scraped from the
     daemon's registry, plus the sentinel serving check's verdict on
     the same metrics the daemon's /healthz serves.

The artifact is validated with tools/check_serve.py before this tool
exits 0 (the generator never commits a record its own validator
rejects).

Round 15 adds `--slo-out SLO_r15.json`: the same run additionally
grades the DEFAULT_OBJECTIVES against the request-duration histogram
the daemon booked (telemetry/slo.py `evaluate_slo` — the exact
arithmetic the live `/slo` endpoint and the sentinel's check_slo
run), records a sample of the per-request ids every response echoed,
and reconstructs the warm probe's critical path from the daemon's
structured access log — validated with tools/check_slo.py (phase
attribution must sum within 5% of measured latency) before the write.

Round 18 adds `--persist-out SERVE_r18.json`: the persistent
executable cache + pipelined dispatch artifact.  The RESTART arm runs
two real subprocesses over one shared `--state-dir` (a subprocess per
phase is not ceremony: any in-process "restart" would keep jax's lru
caches warm and fake the number) — the first cold-compiles and seals
the disk tier, the second restores the warm set at start and answers
its FIRST client request from deserialized executables (`cache:
"disk"`, no warmup call, so the wall IS the cold-restart latency).
The PIPELINE arm replays the same frames through a solo window=1
daemon and a window>1 daemon under a concurrent burst and pins
bit-identity plus the admission/dispatch ledger.  Validated with
tools/check_serve_persist.py (the 10x restart gate lives there)
before the write.

Round 19 adds `--obs-out OBS_r19.json`: the serving-observatory
artifact.  Two live in-process replicas (disjoint registries) serve a
concurrent burst, then `serving/observatory.aggregate` scrapes both
over real HTTP and pools their histograms into the fleet SLO; a
separate paired obs-on/obs-off arm measures the observatory's
request-path overhead (min-paired-delta) and publishes it as the
`ia_observatory_overhead_frac` gauge the sentinel watches.  Validated
with tools/check_obs.py (fleet burn rates must be BIT-EQUAL to
re-merging the committed per-replica histograms) before the write.

Usage:
    python tools/serve_load.py --out SERVE_r13.json [--size 32]
    python tools/serve_load.py --out /tmp/serve.json \\
        --slo-out SLO_r15.json
    python tools/serve_load.py --persist-out SERVE_r18.json
    python tools/serve_load.py --obs-out OBS_r19.json
"""

from __future__ import annotations

import argparse
import base64
import gc
import hashlib
import json
import os
import shutil
import statistics
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request
from typing import List, Optional, Tuple

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from check_fleet_trace import validate_fleet_trace  # noqa: E402
from check_lattice import validate_lattice  # noqa: E402
from check_obs import validate_obs  # noqa: E402
from check_router import validate_router  # noqa: E402
from check_serve import validate_serve  # noqa: E402
from check_serve_persist import validate_serve_persist  # noqa: E402
from check_slo import validate_slo  # noqa: E402


def _post(url: str, body: bytes, timeout: float = 600.0,
          headers: Optional[dict] = None) -> Tuple[int, dict]:
    h = {"Content-Type": "application/json"}
    if headers:
        h.update(headers)
    req = urllib.request.Request(
        url + "/synthesize", data=body, headers=h, method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _quantiles(lat_ms: List[float]) -> Tuple[Optional[float],
                                             Optional[float]]:
    if not lat_ms:
        return None, None
    if len(lat_ms) == 1:
        return round(lat_ms[0], 3), round(lat_ms[0], 3)
    qs = statistics.quantiles(lat_ms, n=100, method="inclusive")
    return round(qs[49], 3), round(qs[98], 3)


def _counter_total(snap: dict, name: str) -> float:
    return float(sum(
        v for v in snap.get(name, {}).get("values", {}).values()
        if isinstance(v, (int, float))
    ))


def _make_inputs(seed: int, size: int):
    """Deterministic (a, a', b) triple — both restart-arm subprocesses
    rebuild the exact same frames from (seed, size) alone, so the
    sha256 comparison pins bit-identity across process boundaries."""
    import numpy as np

    rng = np.random.default_rng(seed)
    return tuple(
        rng.random((size, size, 3)).astype(np.float32) for _ in range(3)
    )


def _frame_body(frame) -> bytes:
    import numpy as np

    return json.dumps({
        "image_b64": base64.b64encode(
            np.ascontiguousarray(frame).tobytes()
        ).decode(),
        "shape": list(frame.shape),
        "dtype": "float32",
    }).encode()


def _sha(doc: dict) -> str:
    return hashlib.sha256(
        base64.b64decode(doc["image_b64"])
    ).hexdigest()


def _serving_check(daemon) -> str:
    health = daemon.health()
    return next(
        c["status"] for c in health["checks"] if c["name"] == "serving"
    )


def run_load(args) -> dict:
    import numpy as np

    from image_analogies_tpu.config import SynthConfig
    from image_analogies_tpu.serving.daemon import SynthDaemon
    from image_analogies_tpu.telemetry.metrics import (
        MetricsRegistry,
        set_registry,
    )

    rng = np.random.default_rng(args.seed)
    size = args.size
    a, ap, b = (
        rng.random((size, size, 3)).astype(np.float32) for _ in range(3)
    )
    cfg = SynthConfig(
        levels=2, matcher="patchmatch", pallas_mode="off",
        em_iters=1, pm_iters=2,
    )
    body = json.dumps({
        "image_b64": base64.b64encode(
            np.ascontiguousarray(b).tobytes()
        ).decode(),
        "shape": [size, size, 3],
        "dtype": "float32",
    }).encode()

    registry = MetricsRegistry()
    prev = set_registry(registry)
    daemon = SynthDaemon(
        a, ap, cfg, registry=registry,
        max_batch=args.max_batch, max_wait_ms=args.max_wait_ms,
        max_queue_depth=args.max_queue_depth,
        cache_capacity=4, max_retries=1,
    ).start()
    request_ids: List[str] = []
    try:
        # -- 1. cache probe: cold (compiles) vs warm repeat shape.
        # The warm probe carries a CLIENT-CHOSEN X-Request-Id (round
        # 15): the echoed id + its access-log critical path prove the
        # request-scoped tracing flows end to end.
        t0 = time.perf_counter()
        code, r = _post(daemon.url, body)
        cold_ms = (time.perf_counter() - t0) * 1000.0
        if code != 200 or r.get("cache") != "miss":
            raise RuntimeError(
                f"cold probe: expected 200/miss, got {code}/"
                f"{r.get('cache')!r} ({r.get('error')})"
            )
        if r.get("request_id"):
            request_ids.append(r["request_id"])
        warm_rid = "slo-warm-probe"
        t0 = time.perf_counter()
        code, r = _post(daemon.url, body,
                        headers={"X-Request-Id": warm_rid})
        warm_ms = (time.perf_counter() - t0) * 1000.0
        if code != 200 or r.get("cache") != "hit":
            raise RuntimeError(
                f"warm probe: expected 200/hit, got {code}/"
                f"{r.get('cache')!r} ({r.get('error')})"
            )
        if r.get("request_id") != warm_rid:
            raise RuntimeError(
                f"warm probe: request_id {r.get('request_id')!r} != "
                f"supplied X-Request-Id {warm_rid!r}"
            )
        request_ids.append(warm_rid)
        print(
            f"serve_load: cache probe cold={cold_ms:.0f} ms "
            f"warm={warm_ms:.0f} ms "
            f"(saved {cold_ms - warm_ms:.0f} ms)", flush=True,
        )

        # -- 2. closed-loop sweep.
        sweep = []
        for clients in args.clients:
            lock = threading.Lock()
            lat_ms: List[float] = []
            counts = {"completed": 0, "shed": 0, "failed": 0,
                      "hits": 0}
            barrier = threading.Barrier(clients)

            def client():
                barrier.wait()
                for _ in range(args.requests_per_client):
                    t0 = time.perf_counter()
                    code, r = _post(daemon.url, body)
                    wall = (time.perf_counter() - t0) * 1000.0
                    with lock:
                        if code == 200:
                            counts["completed"] += 1
                            lat_ms.append(wall)
                            if r.get("cache") == "hit":
                                counts["hits"] += 1
                            if len(request_ids) < 8 and \
                                    r.get("request_id"):
                                request_ids.append(r["request_id"])
                        elif code == 429:
                            counts["shed"] += 1
                        else:
                            counts["failed"] += 1

            threads = [threading.Thread(target=client)
                       for _ in range(clients)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            p50, p99 = _quantiles(lat_ms)
            point = {
                "clients": clients,
                "requests": clients * args.requests_per_client,
                "completed": counts["completed"],
                "shed": counts["shed"],
                "failed": counts["failed"],
                "hit_ratio": round(
                    counts["hits"] / counts["completed"], 3
                ) if counts["completed"] else 0.0,
                "p50_ms": p50,
                "p99_ms": p99,
            }
            sweep.append(point)
            print(f"serve_load: sweep {point}", flush=True)

        # -- 3. final ledger + the sentinel's own verdict.
        snap = registry.to_dict()
        ledger = {
            k: _counter_total(snap, f"ia_serve_{k}_total")
            for k in ("requests", "admitted", "completed", "failed",
                      "shed")
        }
        health = daemon.health()
        serving_check = next(
            c["status"] for c in health["checks"]
            if c["name"] == "serving"
        )
        cache_snap = daemon.cache.snapshot()
        record = {
            "schema_version": 1,
            "kind": "serve",
            "round": 13,
            "proxy_size": size,
            "config": {
                "levels": cfg.levels, "matcher": cfg.matcher,
                "em_iters": cfg.em_iters, "pm_iters": cfg.pm_iters,
                "max_batch": daemon.policy.max_batch,
                "max_wait_ms": daemon.policy.max_wait_ms,
                "max_queue_depth": daemon.admission.max_depth,
                "requests_per_client": args.requests_per_client,
            },
            "cache": {
                "cold_ms": round(cold_ms, 3),
                "warm_ms": round(warm_ms, 3),
                "latency_delta_ms": round(cold_ms - warm_ms, 3),
                "hits": _counter_total(
                    snap, "ia_serve_excache_hits_total"
                ),
                "misses": _counter_total(
                    snap, "ia_serve_excache_misses_total"
                ),
                "evictions": cache_snap["evictions"],
                "resident": cache_snap["resident"],
            },
            "sweep": sweep,
            "ledger": ledger,
            "serving_check": serving_check,
        }

        # -- 4. SLO record (round 15, --slo-out): grade the default
        # objectives against the duration histogram the daemon booked
        # (the same arithmetic /slo serves), and reconstruct the warm
        # probe's critical path from the structured access log.
        slo_record = None
        if args.slo_out:
            from image_analogies_tpu.serving.accesslog import (
                find_request,
                phase_fields,
            )
            from image_analogies_tpu.telemetry.slo import evaluate_slo

            slo_report = evaluate_slo(snap)
            by_name = {o["name"]: o for o in slo_report["objectives"]}
            warm = by_name.get("warm_p99_latency_ms", {})
            access_rec = find_request(daemon.access.path, warm_rid)
            if access_rec is None:
                raise RuntimeError(
                    f"slo: warm probe {warm_rid!r} missing from "
                    f"access log {daemon.access.path}"
                )
            phases = dict(phase_fields(access_rec))
            total_ms = float(access_rec["total_ms"])
            attributed = sum(phases.values())
            slo_record = {
                "schema_version": 1,
                "kind": "slo",
                "round": 15,
                "proxy_size": size,
                "slo": slo_report,
                "p99_warm_ms": warm.get("observed_p99_ms"),
                "availability": by_name.get(
                    "availability", {}
                ).get("availability"),
                "request_ids": request_ids[:8],
                "critical_path": {
                    "request_id": warm_rid,
                    "total_ms": round(total_ms, 3),
                    "phases": {
                        k + "_ms": round(v, 3)
                        for k, v in phases.items()
                    },
                    "attributed_ms": round(attributed, 3),
                    "gap_pct": round(
                        100.0 * abs(total_ms - attributed) / total_ms, 3
                    ) if total_ms > 0 else None,
                },
            }
            print(
                f"serve_load: slo verdict {slo_report['verdict']!r} "
                f"(p99 warm {slo_record['p99_warm_ms']} ms, "
                f"availability {slo_record['availability']}, critical "
                f"path gap {slo_record['critical_path']['gap_pct']}%)",
                flush=True,
            )
        return record, slo_record
    finally:
        daemon.stop()
        set_registry(prev)


def run_persist_phase(args) -> int:
    """Subprocess body for the restart arm (`--phase persist-cold` /
    `persist-restart`).  Runs one daemon over the shared --state-dir,
    posts the probe request(s), and writes measurements + registry
    counters to --json-out for the driver to assemble.

    The restart phase deliberately never calls `daemon.warmup()`: the
    first client request must pay whatever the restore left unpaid, so
    its wall clock IS the cold-restart latency the artifact claims.
    """
    from image_analogies_tpu.config import SynthConfig
    from image_analogies_tpu.serving.daemon import SynthDaemon
    from image_analogies_tpu.telemetry.metrics import (
        MetricsRegistry,
        set_registry,
    )

    a, ap_img, b = _make_inputs(args.seed, args.size)
    cfg = SynthConfig(
        levels=2, matcher="patchmatch", pallas_mode="off",
        em_iters=1, pm_iters=2,
    )
    body = _frame_body(b)
    registry = MetricsRegistry()
    prev = set_registry(registry)
    daemon = SynthDaemon(
        a, ap_img, cfg, registry=registry, max_batch=1,
        max_wait_ms=1.0, observability=False,
        state_dir=args.state_dir,
    ).start()
    try:
        expect = "miss" if args.phase == "persist-cold" else "disk"
        t0 = time.perf_counter()
        code, r = _post(daemon.url, body)
        first_ms = (time.perf_counter() - t0) * 1000.0
        if code != 200 or r.get("cache") != expect:
            raise RuntimeError(
                f"{args.phase}: expected 200/{expect}, got {code}/"
                f"{r.get('cache')!r} ({r.get('error')})"
            )
        out = {
            "phase": args.phase,
            "first_ms": round(first_ms, 3),
            "first_cache": r["cache"],
            "sha256": _sha(r),
        }
        if args.phase == "persist-restart":
            t0 = time.perf_counter()
            code, r2 = _post(daemon.url, body)
            warm_ms = (time.perf_counter() - t0) * 1000.0
            if code != 200 or r2.get("cache") != "hit":
                raise RuntimeError(
                    f"{args.phase}: warm repeat expected 200/hit, got "
                    f"{code}/{r2.get('cache')!r} ({r2.get('error')})"
                )
            if _sha(r2) != out["sha256"]:
                raise RuntimeError(
                    f"{args.phase}: warm repeat diverged from the "
                    "restored response"
                )
            out["warm_ms"] = round(warm_ms, 3)
            out["restore_ms"] = daemon.disk.restore_ms
        snap = registry.to_dict()
        disk_snap = daemon.disk.snapshot()
        out.update({
            "disk": {
                "hits": _counter_total(
                    snap, "ia_excache_disk_hits_total"
                ),
                "misses": _counter_total(
                    snap, "ia_excache_disk_misses_total"
                ),
                "errors": _counter_total(
                    snap, "ia_excache_disk_errors_total"
                ),
                "entries": disk_snap["entries"],
                "stored": disk_snap["stored"],
            },
            "cache_misses": _counter_total(
                snap, "ia_serve_excache_misses_total"
            ),
            "serving_check": _serving_check(daemon),
        })
    finally:
        daemon.stop()
        set_registry(prev)
    _write_json(args.json_out, out)
    print(f"serve_load[{args.phase}]: first request "
          f"{out['first_cache']!r} in {out['first_ms']:.1f} ms",
          flush=True)
    return 0


def _spawn_phase(phase: str, state_dir: str, json_out: str,
                 args) -> dict:
    cmd = [
        sys.executable, os.path.abspath(__file__),
        "--phase", phase, "--state-dir", state_dir,
        "--json-out", json_out,
        "--size", str(args.size), "--seed", str(args.seed),
    ]
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run(cmd, env=env, timeout=900)
    if proc.returncode != 0:
        raise RuntimeError(
            f"persist phase {phase!r} exited {proc.returncode}"
        )
    with open(json_out) as f:
        return json.load(f)


def _run_pipeline_arm(args) -> dict:
    """Pipelined-dispatch arm: replay N distinct frames through a solo
    window=1 daemon, then the same frames as a concurrent burst
    through a window>1 daemon, and pin bit-identity + the ledger."""
    import numpy as np

    from image_analogies_tpu.config import SynthConfig
    from image_analogies_tpu.serving.daemon import SynthDaemon
    from image_analogies_tpu.telemetry.metrics import (
        MetricsRegistry,
        set_registry,
    )

    a, ap_img, _ = _make_inputs(args.seed, args.size)
    rng = np.random.default_rng(args.seed + 1)
    frames = [
        rng.random((args.size, args.size, 3)).astype(np.float32)
        for _ in range(6)
    ]
    bodies = [_frame_body(f) for f in frames]
    cfg = SynthConfig(
        levels=2, matcher="patchmatch", pallas_mode="off",
        em_iters=1, pm_iters=2,
    )

    # -- solo baseline: window=1 serializes dispatch and settle.
    reg0 = MetricsRegistry()
    prev = set_registry(reg0)
    d0 = SynthDaemon(
        a, ap_img, cfg, registry=reg0, max_batch=1, max_wait_ms=1.0,
        observability=False, pipeline_window=1,
    ).start()
    try:
        solo = []
        for bd in bodies:
            code, r = _post(d0.url, bd)
            if code != 200:
                raise RuntimeError(
                    f"pipeline solo baseline: {code} ({r.get('error')})"
                )
            solo.append(_sha(r))
    finally:
        d0.stop()
        set_registry(prev)

    # -- pipelined burst: window>1, all frames posted concurrently.
    reg = MetricsRegistry()
    prev = set_registry(reg)
    daemon = SynthDaemon(
        a, ap_img, cfg, registry=reg, max_batch=1, max_wait_ms=1.0,
        max_queue_depth=32, observability=False,
        pipeline_window=args.pipeline_window,
    ).start()
    try:
        code, r = _post(daemon.url, bodies[0])  # compile the shape
        if code != 200:
            raise RuntimeError(
                f"pipeline warm request: {code} ({r.get('error')})"
            )
        results: List[Optional[dict]] = [None] * len(bodies)
        lat_ms: List[float] = []
        lock = threading.Lock()
        failures: List[str] = []

        def client(i: int) -> None:
            t0 = time.perf_counter()
            try:
                code, r = _post(daemon.url, bodies[i])
            except Exception as e:  # noqa: BLE001
                with lock:
                    failures.append(f"frame {i}: {e!r}")
                return
            wall = (time.perf_counter() - t0) * 1000.0
            with lock:
                if code != 200:
                    failures.append(
                        f"frame {i}: {code} ({r.get('error')})"
                    )
                else:
                    results[i] = r
                    lat_ms.append(wall)

        threads = [
            threading.Thread(target=client, args=(i,))
            for i in range(len(bodies))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if failures:
            raise RuntimeError(f"pipeline burst failed: {failures}")
        bit_identical = all(
            _sha(results[i]) == solo[i] for i in range(len(bodies))
        )
        p50, p99 = _quantiles(lat_ms)
        snap = reg.to_dict()
        ledger = {
            k: _counter_total(snap, f"ia_serve_{k}_total")
            for k in ("requests", "admitted", "completed", "failed",
                      "shed", "dispatches")
        }
        ledger["hits"] = _counter_total(
            snap, "ia_serve_excache_hits_total"
        )
        ledger["misses"] = _counter_total(
            snap, "ia_serve_excache_misses_total"
        )
        inflight_after = int(sum(
            v for v in snap.get(
                "ia_serve_pipeline_inflight_batches", {}
            ).get("values", {}).values()
            if isinstance(v, (int, float))
        ))
        arm = {
            "window": args.pipeline_window,
            "requests": len(bodies),
            "bit_identical": bit_identical,
            "p50_warm_ms": p50,
            "p99_warm_ms": p99,
            "inflight_batches_after": inflight_after,
            "ledger": ledger,
            "serving_check": _serving_check(daemon),
        }
    finally:
        daemon.stop()
        set_registry(prev)
    print(
        f"serve_load: pipeline window={arm['window']} "
        f"bit_identical={arm['bit_identical']} p50={p50} p99={p99} "
        f"ledger={ledger}", flush=True,
    )
    return arm


def run_persist(args) -> dict:
    """Driver for the round-18 artifact: subprocess restart arm +
    in-process pipeline arm, assembled into one serve_persist record.
    """
    state = tempfile.mkdtemp(prefix="serve-persist-")
    cold = _spawn_phase(
        "persist-cold", state, os.path.join(state, "cold.json"), args
    )
    restart = _spawn_phase(
        "persist-restart", state,
        os.path.join(state, "restart.json"), args,
    )
    if cold["serving_check"] != "ok":
        raise RuntimeError(
            f"persist-cold serving check {cold['serving_check']!r}"
        )
    pipeline = _run_pipeline_arm(args)
    cold_ms = cold["first_ms"]
    restart_ms = restart["first_ms"]
    record = {
        "schema_version": 1,
        "kind": "serve_persist",
        "round": 18,
        "proxy_size": args.size,
        "config": {
            "levels": 2, "matcher": "patchmatch",
            "em_iters": 1, "pm_iters": 2,
            "pipeline_window": args.pipeline_window,
        },
        "persist": {
            "cold_ms": cold_ms,
            "cold_restart_ms": restart_ms,
            "restart_speedup": round(cold_ms / restart_ms, 1),
            "warm_ms": restart["warm_ms"],
            "restore_ms": restart["restore_ms"],
            "first_restart_cache": restart["first_cache"],
            "bit_identical": restart["sha256"] == cold["sha256"],
            "disk": {
                "hits": restart["disk"]["hits"],
                "misses": restart["disk"]["misses"],
                "errors": restart["disk"]["errors"],
                "entries": restart["disk"]["entries"],
            },
            "cache_misses": restart["cache_misses"],
            "serving_check": restart["serving_check"],
        },
        "pipeline": pipeline,
    }
    return record


def run_obs(args) -> dict:
    """Round-19 observatory arm (`--obs-out`): two live in-process
    daemon replicas (disjoint registries, same style pair) under a
    concurrent load burst, aggregated OVER REAL HTTP by
    serving/observatory.aggregate — the acceptance path for the
    pooled-not-averaged fleet burn-rate contract (check_obs re-derives
    the fleet SLO from the committed per-replica histograms and
    requires bit-equality).

    The overhead pin runs as a separate paired arm: one daemon with an
    aggressively-ticking observatory (20 Hz sampler — far hotter than
    the 0.2 Hz production default) against one with the plane disabled,
    alternated warm requests, min-paired-delta over median base (the
    round-12/15/16 overhead-measurement discipline: the minimum is the
    run where scheduler noise was stillest).  The measured fraction is
    published as `ia_observatory_overhead_frac` on both replicas —
    the gauge the sentinel's telemetry-overhead check watches — and
    recorded in the artifact."""
    from image_analogies_tpu.config import SynthConfig
    from image_analogies_tpu.serving.daemon import SynthDaemon
    from image_analogies_tpu.serving.observatory import aggregate
    from image_analogies_tpu.telemetry.anomaly import (
        AnomalyConfig,
        baseline_from_record,
    )

    from image_analogies_tpu.telemetry.metrics import MetricsRegistry

    a, ap_img, b = _make_inputs(args.seed, args.size)
    cfg = SynthConfig(
        levels=2, matcher="patchmatch", pallas_mode="off",
        em_iters=1, pm_iters=2,
    )
    body = _frame_body(b)
    baseline = baseline_from_record(
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "SERVE_r18.json")
    )
    anomaly_cfg = AnomalyConfig(baseline_p99_ms=baseline)

    def make_daemon(reg, interval):
        return SynthDaemon(
            a, ap_img, cfg, registry=reg, max_batch=1,
            max_wait_ms=1.0, max_queue_depth=16, cache_capacity=4,
            obs_interval_s=interval, obs_capacity=64,
            anomaly_config=anomaly_cfg,
        ).start()

    # -- paired overhead arm FIRST: the replica pair's rings hold
    # capacity x interval (~16 s) of history, so the burst must be
    # scraped promptly — anything slow between burst and scrape would
    # rotate the burst out of every window.  Measuring first also
    # lets the gauge be live in both registries before any scrape.
    overhead = _measure_obs_overhead(a, ap_img, cfg, body, anomaly_cfg)

    # -- replica pair under load ------------------------------------
    regs = [MetricsRegistry(), MetricsRegistry()]
    for reg in regs:
        reg.gauge(
            "ia_observatory_overhead_frac",
            "measured observatory (ring sampler + anomaly "
            "watches) request-path overhead fraction",
        ).set(round(overhead, 4))
    daemons = [make_daemon(reg, 0.25) for reg in regs]
    try:
        # One request per replica first: the process-global jit cache
        # makes the second replica's compile nearly free, and both
        # replicas then serve the burst warm.
        for d in daemons:
            code, r = _post(d.url, body)
            if code != 200:
                raise RuntimeError(
                    f"obs warm request: {code} ({r.get('error')})"
                )
            # Window-epoch boundary: the cold compile above is warmup,
            # not traffic — reset so every served window (and the
            # anomaly detector's latency watch) deltifies against
            # post-warmup state.
            d.obs.reset()
        # Burst each replica with concurrent clients, ONE REPLICA AT A
        # TIME: two co-located in-process daemons share the host's
        # device set, and concurrent executions of two different
        # collective-bearing executables can starve XLA's shared
        # participant pool into a rendezvous deadlock.  A real fleet
        # is separate processes; in-process co-location is this
        # harness's artifact, so the harness serializes across
        # daemons while keeping per-daemon client concurrency.
        lock = threading.Lock()
        lat_ms: List[float] = []
        failures: List[str] = []

        def client(d, i: int) -> None:
            for _ in range(args.requests_per_client):
                t0 = time.perf_counter()
                try:
                    code, r = _post(d.url, body)
                except Exception as e:  # noqa: BLE001
                    with lock:
                        failures.append(f"client {i}: {e!r}")
                    return
                wall = (time.perf_counter() - t0) * 1000.0
                with lock:
                    if code == 200:
                        lat_ms.append(wall)
                    else:
                        failures.append(
                            f"client {i}: {code} ({r.get('error')})"
                        )

        for d in daemons:
            threads = [
                threading.Thread(target=client, args=(d, i))
                for i in range(3)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        if failures:
            raise RuntimeError(f"obs burst failed: {failures}")

        # The newest ring snapshot lags traffic by up to one tick
        # interval — wait until every burst request is inside each
        # replica's window before scraping, so the committed windows
        # carry real post-warmup rates (status "ok").
        def in_window(d) -> int:
            cells = (d.obs.window(None).get("histograms") or {}).get(
                "ia_request_duration_ms") or {}
            return sum(int(c["count"] or 0) for c in cells.values())

        want = 3 * args.requests_per_client
        deadline = time.monotonic() + 15.0
        while any(in_window(d) < want for d in daemons):
            if time.monotonic() > deadline:
                raise RuntimeError(
                    "obs windows never captured the burst: "
                    f"{[in_window(d) for d in daemons]} < {want}"
                )
            time.sleep(0.05)

        record = aggregate([d.url for d in daemons], span_s=None)
        p50, p99 = _quantiles(lat_ms)
        record.update({
            "proxy_size": args.size,
            "config": {
                "levels": cfg.levels, "matcher": cfg.matcher,
                "em_iters": cfg.em_iters, "pm_iters": cfg.pm_iters,
                "obs_interval_s": 0.25,
                "baseline_p99_ms": baseline,
            },
            "load": {
                "requests": 6 * args.requests_per_client + 2,
                "completed": len(lat_ms) + 2,
                "p50_ms": p50,
                "p99_ms": p99,
            },
            "observatory_overhead_frac": round(overhead, 4),
        })
        for d in daemons:
            anomaly_check = next(
                c for c in d.health()["checks"] if c["name"] == "anomaly"
            )
            if anomaly_check["status"] not in ("ok", "degraded"):
                raise RuntimeError(
                    f"anomaly sentinel check {anomaly_check['status']!r}"
                    " — detector never graded"
                )
        return record
    finally:
        for d in daemons:
            d.stop()


def _measure_obs_overhead(a, ap_img, cfg, body, anomaly_cfg) -> float:
    """Min-paired-delta overhead of the observatory plane: alternated
    warm requests between an obs-on (20 Hz sampler) and an obs-off
    daemon."""
    from image_analogies_tpu.serving.daemon import SynthDaemon
    from image_analogies_tpu.telemetry.metrics import MetricsRegistry

    def spawn(interval):
        return SynthDaemon(
            a, ap_img, cfg, registry=MetricsRegistry(), max_batch=1,
            max_wait_ms=1.0, obs_interval_s=interval,
            anomaly_config=anomaly_cfg,
        ).start()

    d_obs = spawn(0.05)
    d_base = spawn(0.0)
    try:
        for d in (d_obs, d_base):
            code, r = _post(d.url, body)
            if code != 200:
                raise RuntimeError(
                    f"overhead warm request: {code} ({r.get('error')})"
                )
        bases, deltas = [], []
        for _ in range(8):
            t0 = time.perf_counter()
            _post(d_base.url, body)
            base = (time.perf_counter() - t0) * 1000.0
            t0 = time.perf_counter()
            _post(d_obs.url, body)
            obs = (time.perf_counter() - t0) * 1000.0
            bases.append(base)
            deltas.append(obs - base)
        return max(0.0, min(deltas) / statistics.median(bases))
    finally:
        d_obs.stop()
        d_base.stop()


def run_lattice(args) -> dict:
    """Round 20 shape-lattice arm: one lattice-on daemon (the full
    bucket grid precompiled by warmup) vs one lattice-off reference,
    driven through a NEVER-SEEN-SHAPE burst.

    The claims the artifact commits, all enforced by
    tools/check_lattice.py before the write:

      - bounded keys: after warming the grid, the burst's arbitrary
        shapes add ZERO executable-cache entries (every in-bounds
        request keys onto a lattice bucket);
      - hit-everything: every burst request — shapes the daemon has
        never seen, including a 1x1 degenerate and exact bucket
        bounds — is a cache HIT, and its p99 sits within 2x the warm
        p99 of repeats on the top bucket shape (vs the ~24x
        compile-priced cold shapes cost per SERVE_r18);
      - bit-identity: the lattice's cropped output equals the
        lattice-off daemon's answer for the same frame edge-padded
        client-side (the crop(serve(pad(F))) contract), and an
        exactly-on-bucket frame is byte-identical with no padding at
        all;
      - honest bypass: a frame over the top rung takes the exact-key
        path as a real miss, booked under path="bypass".
    """
    import numpy as np

    from image_analogies_tpu.config import SynthConfig
    from image_analogies_tpu.serving.daemon import SynthDaemon
    from image_analogies_tpu.serving.lattice import (
        parse_lattice_spec,
        plan_lattice,
    )
    from image_analogies_tpu.telemetry.metrics import (
        MetricsRegistry,
        set_registry,
    )

    a, ap_img, _ = _make_inputs(args.seed, args.size)
    cfg = SynthConfig(
        levels=2, matcher="patchmatch", pallas_mode="off",
        em_iters=1, pm_iters=2,
    )
    lat_cfg = parse_lattice_spec(args.lattice_spec)
    if lat_cfg is None:
        raise RuntimeError(
            f"--lattice-spec {args.lattice_spec!r} parses to OFF"
        )
    plan = plan_lattice(lat_cfg)
    lat = plan.lattice
    print(
        f"serve_load: lattice[{plan.source}] rungs {list(lat.rungs)} "
        f"= {lat.size} buckets (growth {lat.growth:g})", flush=True,
    )

    reg = MetricsRegistry()
    prev = set_registry(reg)
    daemon = SynthDaemon(
        a, ap_img, cfg, registry=reg, max_batch=1, max_wait_ms=1.0,
        cache_capacity=lat.size + 4, max_retries=1, lattice=plan,
        obs_interval_s=0,
    ).start()
    ref = SynthDaemon(
        a, ap_img, cfg, registry=MetricsRegistry(), max_batch=1,
        max_wait_ms=1.0, cache_capacity=lat.size + 4, max_retries=1,
        obs_interval_s=0,
    ).start()
    rng = np.random.default_rng(args.seed + 20)
    try:
        # -- warmup: the whole grid, before any client traffic.
        t0 = time.perf_counter()
        warm_report = daemon.warmup([])
        warmup_ms = (time.perf_counter() - t0) * 1000.0
        resident_warm = daemon.cache.snapshot()["resident"]
        if resident_warm != lat.size:
            raise RuntimeError(
                f"warmup left {resident_warm} executables resident, "
                f"expected the full grid ({lat.size})"
            )

        def post_expect(url, frame, want_cache=None):
            t0 = time.perf_counter()
            code, r = _post(url, _frame_body(frame))
            wall = (time.perf_counter() - t0) * 1000.0
            if code != 200:
                raise RuntimeError(
                    f"request {frame.shape}: {code} ({r.get('error')})"
                )
            if want_cache is not None and r.get("cache") != want_cache:
                raise RuntimeError(
                    f"request {frame.shape}: cache "
                    f"{r.get('cache')!r}, expected {want_cache!r}"
                )
            return wall, r

        def decode(r):
            return np.frombuffer(
                base64.b64decode(r["image_b64"]), np.float32
            ).reshape(r["shape"])

        # -- warm baseline: repeats on the TOP bucket shape (the
        # largest canvas any in-bounds request can run on, so the
        # burst's per-request compute is bounded by the baseline's).
        # GC is parked across both measured sections: the daemon runs
        # in-process, and a collection pause landing inside one
        # ~15 ms request reads as a fake multiple-of-warm cold wall.
        top = lat.top
        warm_frame = rng.random((top, top, 3)).astype(np.float32)
        gc.collect()
        gc.disable()
        warm_walls = []
        for _ in range(args.requests_per_client * 4):
            wall, _r = post_expect(daemon.url, warm_frame, "hit")
            warm_walls.append(wall)
        p50_warm, p99_warm = _quantiles(warm_walls)

        # -- never-seen-shape burst: random in-bounds shapes the
        # daemon has never dispatched, plus the adversarial corners —
        # a 1x1 degenerate frame and an exactly-on-bucket-bound
        # shape.  Every one must be a cache hit.
        shapes = set()
        while len(shapes) < 12:
            h = int(rng.integers(max(1, lat.rungs[0] - 7), top + 1))
            w = int(rng.integers(max(1, lat.rungs[0] - 7), top + 1))
            if (h, w) != (top, top):
                shapes.add((h, w))
        burst_shapes = sorted(shapes) + [(1, 1), (lat.rungs[0], top)]
        burst_walls = []
        identity = {"verified": 0, "mismatched": 0}
        for i, (h, w) in enumerate(burst_shapes):
            frame = rng.random((h, w, 3)).astype(np.float32)
            wall, r = post_expect(daemon.url, frame, "hit")
            burst_walls.append(wall)
            if list(r["shape"]) != [h, w, 3]:
                raise RuntimeError(
                    f"burst {h}x{w}: response shape {r['shape']}"
                )
            if i < 4 or (h, w) in ((1, 1), (lat.rungs[0], top)):
                # Bit-identity probe: the unbucketed daemon's answer
                # for the same frame edge-padded client-side, cropped
                # back, must match byte for byte.
                bh, bw = lat.bucket_for(h, w)
                padded = np.pad(
                    frame, [(0, bh - h), (0, bw - w), (0, 0)],
                    mode="edge",
                )
                _w, rr = post_expect(ref.url, padded)
                same = np.array_equal(
                    decode(r), decode(rr)[:h, :w]
                )
                identity["verified" if same else "mismatched"] += 1
        p50_cold, p99_cold = _quantiles(burst_walls)
        gc.enable()
        resident_burst = daemon.cache.snapshot()["resident"]

        # -- on-bucket identity: a frame already on a bucket shape
        # rides untouched — byte-identical to the lattice-off path.
        on_frame = rng.random(
            (lat.rungs[0], lat.rungs[0], 3)
        ).astype(np.float32)
        _w1, r1 = post_expect(daemon.url, on_frame, "hit")
        _w2, r2 = post_expect(ref.url, on_frame)
        on_bucket_identical = r1["image_b64"] == r2["image_b64"]

        # -- bypass: over the top rung -> exact-key path, honest miss.
        by_frame = rng.random((top + 1, top, 3)).astype(np.float32)
        _w, r_by = post_expect(daemon.url, by_frame, "miss")
        resident_final = daemon.cache.snapshot()["resident"]

        snap = reg.to_dict()
        admissions = {
            path: float(snap.get(
                "ia_lattice_admissions_total", {}
            ).get("values", {}).get(f'{{path="{path}"}}', 0.0))
            for path in ("bucketed", "exact", "bypass")
        }
        card_vals = snap.get(
            "ia_serve_shape_cardinality", {}
        ).get("values", {})
        lattice_serving = daemon._lattice_snapshot()
        record = {
            "schema_version": 1,
            "kind": "lattice",
            "round": 20,
            "generated_by": "tools/serve_load.py --lattice-out",
            "proxy_size": args.size,
            "config": {
                "levels": cfg.levels, "matcher": cfg.matcher,
                "em_iters": cfg.em_iters, "pm_iters": cfg.pm_iters,
                "lattice_spec": args.lattice_spec,
            },
            "plan": plan.as_dict(),
            "warmup": {
                "buckets": lat.size,
                "resident_after_warmup": resident_warm,
                "wall_ms": round(warmup_ms, 1),
                "shapes_compiled": len(warm_report),
            },
            "warm": {
                "shape": [top, top, 3],
                "requests": len(warm_walls),
                "p50_ms": p50_warm,
                "p99_ms": p99_warm,
            },
            "burst": {
                "shapes": [list(s) for s in burst_shapes],
                "requests": len(burst_walls),
                "all_hits": True,
                "p50_cold_ms": p50_cold,
                "p99_cold_ms": p99_cold,
            },
            "p99_cold_over_warm": round(p99_cold / p99_warm, 4),
            "bit_identity": dict(
                identity, on_bucket_identical=on_bucket_identical,
            ),
            "bypass": {
                "shape": [top + 1, top, 3],
                "cache": r_by.get("cache"),
                "admissions": admissions["bypass"],
            },
            "exec_keys": {
                "bound": lat.size,
                "resident_after_warmup": resident_warm,
                "resident_after_burst": resident_burst,
                "resident_final": resident_final,
                "bypass_keys": resident_final - resident_burst,
            },
            "cardinality": {
                "raw": card_vals.get('{view="raw"}'),
                "bucketed": card_vals.get('{view="bucketed"}'),
            },
            "waste": {
                "mean_bucket_waste_frac":
                    lattice_serving["mean_bucket_waste_frac"],
                "worst_waste_frac_bound":
                    plan.chosen.worst_waste_frac,
            },
            "admissions": admissions,
            "serving_check": _serving_check(daemon),
        }
        return record
    finally:
        gc.enable()  # idempotent; covers a mid-measurement raise
        daemon.stop()
        ref.stop()
        set_registry(prev)


def run_router(args) -> dict:
    """Round-21 fleet-router arm (`--router-out`): `ia-synth serve`
    SUBPROCESS replicas (per-replica state dirs, one SHARED
    --warm-dir) behind an in-process FleetRouter, graded under the
    weak-scaling protocol this box can honestly support.

    On a single core, strong scaling (one client, N replicas) is a
    physical no-op: aggregate compute throughput is one core no
    matter how many replicas share it.  What N replicas DO buy is
    overlap of the batching policy's head-of-line wait (max_wait_ms)
    across independent clients — replica i can sit in its coalesce
    wait while replica j computes.  So the protocol is one closed-loop
    client per replica (clients_per_replica = 1, the load grows WITH
    the fleet) and the claim is throughput per wall-second:
    1 client / 1 replica vs N clients / N replicas, identical
    per-replica batching policy, identical request mix, warm on both
    sides.  Expected scaling = N*(w + c) / (w + N*c) for head wait w
    and per-request compute c — the committed floor is 1.6x.

    Also measured here: the mid-burst replica add (spawn a fresh
    replica over the shared warm tier while 3 clients burst, route
    its FIRST request, compare against the fleet's warm p99), the
    session-affinity hit-rate matrix, and the embedded chaos
    replica-kill arm (tools/chaos_serve.py)."""
    import numpy as np

    import chaos_serve
    from image_analogies_tpu.serving.router import FleetRouter
    from image_analogies_tpu.telemetry.metrics import MetricsRegistry
    from image_analogies_tpu.utils.io import save_image

    size = args.size
    reqs = max(8, args.requests_per_client)
    a, ap_img, _ = _make_inputs(args.seed, size)
    rng = np.random.default_rng(args.seed + 21)
    frames = [
        rng.random((size, size, 3)).astype(np.float32)
        for _ in range(8)
    ]
    asset_dir = tempfile.mkdtemp(prefix="ia_router_assets_")
    warm = tempfile.mkdtemp(prefix="ia_router_warm_")
    states = [tempfile.mkdtemp(prefix=f"ia_router_s{i}_")
              for i in range(4)]
    traces = [tempfile.mkdtemp(prefix=f"ia_router_t{i}_")
              for i in range(4)]
    a_path = os.path.join(asset_dir, "a.png")
    ap_path = os.path.join(asset_dir, "ap.png")
    save_image(a_path, a)
    save_image(ap_path, ap_img)
    # The replicas' OWN policy, identical on every replica and in both
    # phases: max_batch 4 / max_wait_ms 75 (the round-13 coalesce
    # family).  The wait is the quantity the fleet overlaps.
    wait_ms = 75.0
    policy = ("--max-batch", "4", "--max-wait-ms", str(wait_ms),
              "--max-queue-depth", "32", "--warm-dir", warm)

    def spawn(i):
        return chaos_serve._spawn_serve(
            a_path, ap_path, traces[i], state_dir=states[i],
            extra=policy,
        )

    def closed_loop(n, lat_out, routed_out, stop=None):
        for k in range(n):
            if stop is not None and stop.is_set():
                return
            t0 = time.perf_counter()
            code, _doc, hdrs = chaos_serve._post(
                router.url, _frame_body(frames[k % len(frames)])
            )
            dt = (time.perf_counter() - t0) * 1000.0
            if code == 200:
                lat_out.append(dt)
                rep = hdrs.get("X-Routed-To")
                routed_out[rep] = routed_out.get(rep, 0) + 1

    def phase_cells(nrep, lat, wall_s):
        p50, p99 = _quantiles(lat)
        return {
            "replicas": nrep, "clients": nrep,
            "requests": len(lat), "wall_s": wall_s,
            "throughput_rps": len(lat) / wall_s,
            "p50_ms": p50, "p99_ms": p99,
        }

    procs = []
    router = FleetRouter(MetricsRegistry(), poll_interval_s=0.2)
    try:
        router.start()
        # ---- phase 1: single replica, single closed-loop client.
        p0, u0 = spawn(0)
        procs.append(p0)
        router.add_replica(u0, name="r0")
        code, _d, _h = chaos_serve._post(
            router.url, _frame_body(frames[0])
        )  # untimed warmup: the one cold compile, sealed to the tier
        if code != 200:
            raise RuntimeError(f"router warmup request failed: {code}")
        lat1: List[float] = []
        spread1: dict = {}
        gc.disable()
        t0 = time.perf_counter()
        closed_loop(reqs, lat1, spread1)
        wall1 = time.perf_counter() - t0
        gc.enable()
        single = phase_cells(1, lat1, wall1)

        # ---- phase 2: grow to 3 replicas + 3 clients (weak scaling).
        for i in (1, 2):
            p, u = spawn(i)
            procs.append(p)
            router.add_replica(u, name=f"r{i}")
        # One untimed settling round so both phases measure the same
        # steady state (the single phase had its warmup request too).
        for f in frames[:3]:
            chaos_serve._post(router.url, _frame_body(f))
        lat3: List[float] = []
        spread3: dict = {}
        threads = [
            threading.Thread(
                target=closed_loop, args=(reqs, lat3, spread3)
            )
            for _ in range(3)
        ]
        gc.disable()
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall3 = time.perf_counter() - t0
        gc.enable()
        fleet = phase_cells(3, lat3, wall3)
        fleet["per_replica_requests"] = spread3

        # ---- phase 3: add a replica MID-BURST over the warm tier.
        stop = threading.Event()
        bg = [
            threading.Thread(
                target=closed_loop, args=(10_000, [], {}, stop)
            )
            for _ in range(3)
        ]
        for t in bg:
            t.start()
        try:
            t_spawn = time.perf_counter()
            p3, u3 = spawn(3)
            procs.append(p3)
            spawn_ms = (time.perf_counter() - t_spawn) * 1000.0
            router.add_replica(u3, name="r3")
            first_ms = None
            attempts = 0
            for _ in range(20):
                attempts += 1
                t0 = time.perf_counter()
                code, _d, hdrs = chaos_serve._post(
                    router.url, _frame_body(frames[attempts % 8])
                )
                dt = (time.perf_counter() - t0) * 1000.0
                if code == 200 and hdrs.get("X-Routed-To") == "r3":
                    first_ms = dt
                    break
        finally:
            stop.set()
            for t in bg:
                t.join()
        if first_ms is None:
            raise RuntimeError(
                "mid-burst replica never won a routed request"
            )
        disk_snap = chaos_serve._get_json(u3 + "/serving").get(
            "disk_cache"
        )
        warm_start = {
            "replica": "r3",
            "spawn_to_live_ms": round(spawn_ms, 1),
            "route_attempts": attempts,
            "first_request_ms": first_ms,
            "fleet_warm_p99_ms": fleet["p99_ms"],
            "warm_p99_ratio": first_ms / fleet["p99_ms"],
            "disk_cache": disk_snap,
        }

        # ---- phase 4: session affinity (4 sessions x 3 frames,
        # interleaved so every replica stays a live candidate between
        # a session's consecutive frames).
        before = dict(router.affinity_counts)
        n_sessions, n_frames = 4, 3
        for k in range(n_frames):
            for s in range(n_sessions):
                code, _d, _h = chaos_serve._post(
                    router.url,
                    chaos_serve._session_body(
                        frames[(s + k) % len(frames)], f"aff-{s}"
                    ),
                )
                if code != 200:
                    raise RuntimeError(
                        f"affinity frame failed: {code}"
                    )
        after = dict(router.affinity_counts)
        delta = {k: after[k] - before[k] for k in after}
        expected_hits = n_sessions * (n_frames - 1)
        affinity = {
            "sessions": n_sessions,
            "frames_per_session": n_frames,
            "hit": delta["hit"], "new": delta["new"],
            "repin": delta["repin"],
            "expected_hits": expected_hits,
            "hit_rate": (delta["hit"] / expected_hits
                         if expected_hits else None),
        }
        fleet_snapshot = {
            "replicas": router.replicas(),
            "proxied": router.proxied,
            "proxy_errors": router.proxy_errors,
            "retries": router.retries,
        }
    finally:
        router.stop()
        for p in procs:
            chaos_serve._reap(p)
        for d in (asset_dir, warm, *states, *traces):
            shutil.rmtree(d, ignore_errors=True)

    # ---- phase 5: the chaos replica-kill arm (own fleet + dirs).
    asset_dir2 = tempfile.mkdtemp(prefix="ia_router_assets2_")
    try:
        a_path2 = os.path.join(asset_dir2, "a.png")
        ap_path2 = os.path.join(asset_dir2, "ap.png")
        save_image(a_path2, a)
        save_image(ap_path2, ap_img)
        chaos = chaos_serve.arm_replica_kill_midburst(
            a_path2, ap_path2, size
        )
    finally:
        shutil.rmtree(asset_dir2, ignore_errors=True)

    return {
        "schema_version": 1,
        "kind": "router",
        "round": 21,
        "generated_by": "tools/serve_load.py --router-out",
        "proxy_size": size,
        "config": {
            "levels": 2, "matcher": "patchmatch", "em_iters": 1,
            "pm_iters": 2, "max_batch": 4, "max_wait_ms": wait_ms,
            "shared_warm_dir": True,
        },
        "protocol": {
            "mode": "weak_scaling",
            "clients_per_replica": 1,
            "requests_per_client": reqs,
            "note": (
                "single-core box: strong scaling is physically "
                "impossible (aggregate compute = 1 core), so the "
                "fleet claim is head-of-line-wait overlap under one "
                "closed-loop client per replica — N*(w+c)/(w+N*c) "
                f"with w = max_wait_ms = {wait_ms:g}"
            ),
        },
        "single": single,
        "fleet": fleet,
        "scaling_factor": (fleet["throughput_rps"]
                           / single["throughput_rps"]),
        "warm_start": warm_start,
        "affinity": affinity,
        "chaos": chaos,
        "fleet_snapshot": fleet_snapshot,
    }


def run_fleet_trace(args) -> dict:
    """Round-22 fleet-trace arm (`--trace-out`): two `ia-synth serve`
    SUBPROCESS replicas (per-replica state dirs, shared warm tier)
    behind an in-process TRACED FleetRouter (span tracer + flight ring
    + router access log), exercised through every arm the trace fabric
    claims:

      - MAIN: the first routed request (cold compile — real named
        work) fetched back over HTTP via the discovery file
        (`fetch_fleet_trace`, the exact `ia-synth trace --fleet`
        path) and joined into one waterfall; the committed
        `critical_path_coverage` must re-derive >= 0.95.
      - WARM: a warm repeat's joined trace, committed for reference
        (structure-validated, not coverage-gated: a ~15 ms request's
        HTTP framing is honestly reported as gap, not hidden).
      - RETRY: r1 is drained AT THE DAEMON (the router's poller is
        parked, so the router still believes it live), a pinned
        session's next frame hits the draining 503 and re-routes —
        the retry cost becomes a named proxy_attempt span, and the
        access log's retry-reason entries must reconcile EXACTLY with
        `ia_route_retries_total`.
      - MIGRATION: `drain_replica` migrates the remaining pinned
        session to the survivor; the drain_migration span tree and
        `ia_route_migration_ms` make the move visible, and the
        session's next frame must route to the adoption target.
      - OVERHEAD: min-paired-delta between this traced router and an
        untraced one over the same fleet, published as the
        `ia_route_trace_overhead_frac` gauge the sentinel watches;
        the committed fraction must stay under 2%.
    """
    import chaos_serve
    from image_analogies_tpu.serving.fleettrace import (
        fetch_fleet_trace,
        join_fleet_trace,
    )
    from image_analogies_tpu.serving.router import FleetRouter
    from image_analogies_tpu.telemetry.anomaly import fleet_watches
    from image_analogies_tpu.telemetry.flight import FlightRecorder
    from image_analogies_tpu.telemetry.metrics import MetricsRegistry
    from image_analogies_tpu.telemetry.spans import Tracer
    from image_analogies_tpu.utils.io import save_image

    size = args.size
    a, ap_img, b = _make_inputs(args.seed, size)
    asset_dir = tempfile.mkdtemp(prefix="ia_trace_assets_")
    warm = tempfile.mkdtemp(prefix="ia_trace_warm_")
    states = [tempfile.mkdtemp(prefix=f"ia_trace_s{i}_")
              for i in range(2)]
    traces = [tempfile.mkdtemp(prefix=f"ia_trace_t{i}_")
              for i in range(2)]
    router_dir = tempfile.mkdtemp(prefix="ia_trace_router_")
    a_path = os.path.join(asset_dir, "a.png")
    ap_path = os.path.join(asset_dir, "ap.png")
    save_image(a_path, a)
    save_image(ap_path, ap_img)
    body = _frame_body(b)
    policy = ("--warm-dir", warm)

    reg = MetricsRegistry()
    tracer = Tracer(registry=reg)
    flight = FlightRecorder(
        tracer, reg,
        path=os.path.join(router_dir, "flight.json"), capacity=2048,
    )
    tracer.add_observer(flight.observe)
    discovery_path = os.path.join(router_dir, "discovery.json")
    # poll_interval_s is parked high on BOTH routers: the retry arm
    # depends on the router's view of r1 going stale between the
    # daemon-level drain and the pinned pick.
    router = FleetRouter(
        reg, tracer=tracer, poll_interval_s=60.0, flight=flight,
        discovery_path=discovery_path,
        access_log_path=os.path.join(router_dir, "access.jsonl"),
    )
    bare = FleetRouter(MetricsRegistry(), poll_interval_s=60.0)
    procs = []
    try:
        router.start()
        bare.start()
        for i in range(2):
            p, u = chaos_serve._spawn_serve(
                a_path, ap_path, traces[i], state_dir=states[i],
                extra=policy,
            )
            procs.append(p)
            router.add_replica(u, name=f"r{i}")
            bare.add_replica(u, name=f"r{i}")

        # ---- MAIN arm: the first routed request (cold compile).
        main_rid = "r22-main"
        t0 = time.perf_counter()
        code, doc, hdrs = chaos_serve._post(router.url, body,
                                            rid=main_rid)
        main_wall_ms = (time.perf_counter() - t0) * 1000.0
        if code != 200:
            raise RuntimeError(
                f"main arm: {code} ({doc.get('error')})"
            )
        if doc.get("request_id") != main_rid:
            raise RuntimeError(
                f"main arm: request_id {doc.get('request_id')!r} not "
                "echoed"
            )
        main_replica = hdrs.get("X-Routed-To")

        # Warm the OTHER replica (shared warm tier: a disk restore,
        # not a second compile) so every later arm runs warm.
        other = next(u for n, u in
                     [(h["name"], h["url"]) for h in router.replicas()]
                     if n != main_replica)
        code, doc, _h = chaos_serve._post(other, body)
        if code != 200:
            raise RuntimeError(f"warm other replica: {code}")

        # ---- WARM joined trace (reference, not coverage-gated).
        warm_rid = "r22-warm"
        code, doc, _h = chaos_serve._post(router.url, body,
                                          rid=warm_rid)
        if code != 200:
            raise RuntimeError(f"warm arm: {code}")

        # ---- OVERHEAD arm: traced vs bare router, min-paired-delta.
        gc.collect()
        gc.disable()
        bases, deltas = [], []
        for _ in range(8):
            t0 = time.perf_counter()
            chaos_serve._post(bare.url, body)
            base = (time.perf_counter() - t0) * 1000.0
            t0 = time.perf_counter()
            chaos_serve._post(router.url, body)
            traced_ms = (time.perf_counter() - t0) * 1000.0
            bases.append(base)
            deltas.append(traced_ms - base)
        gc.enable()
        overhead_frac = max(0.0, min(deltas) / statistics.median(bases))
        reg.gauge(
            "ia_route_trace_overhead_frac",
            "measured router trace-fabric (span tree + access-log "
            "write) request-path overhead fraction",
        ).set(round(overhead_frac, 4))

        # ---- sessions: pin retry + migration sessions to r1 and
        # serve them there so r1's state dir holds real session state.
        victim = "r1" if main_replica != "r1" else "r0"
        survivor = "r0" if victim == "r1" else "r1"
        with router._lock:
            router._affinity["r22-retry"] = victim
            router._affinity["r22-mig"] = victim
        for sid in ("r22-retry", "r22-mig"):
            code, _d, hdrs = chaos_serve._post(
                router.url, chaos_serve._session_body(b, sid)
            )
            if code != 200 or hdrs.get("X-Routed-To") != victim:
                raise RuntimeError(
                    f"session {sid}: {code} routed to "
                    f"{hdrs.get('X-Routed-To')!r}, wanted {victim!r}"
                )

        # ---- RETRY arm: drain the victim AT THE DAEMON (router's
        # poller is parked, so its table is stale), then post the
        # pinned session's next frame — draining 503, one re-route.
        victim_url = next(h["url"] for h in router.replicas()
                          if h["name"] == victim)
        urllib.request.urlopen(urllib.request.Request(
            victim_url + "/drain", data=b"{}", method="POST",
            headers={"Content-Type": "application/json"},
        ), timeout=60.0).read()
        retry_rid = "r22-retry-1"
        code, doc, hdrs = chaos_serve._post(
            router.url, chaos_serve._session_body(b, "r22-retry"),
            rid=retry_rid,
        )
        if code != 200 or hdrs.get("X-Routed-To") != survivor:
            raise RuntimeError(
                f"retry arm: {code} routed to "
                f"{hdrs.get('X-Routed-To')!r}, wanted {survivor!r}"
            )

        # ---- MIGRATION arm: drain_replica migrates r22-mig to the
        # survivor; its next frame must follow the adoption.
        mig_report = router.drain_replica(victim, wait_s=60.0)
        if "r22-mig" not in mig_report.get("sessions_migrated", []):
            raise RuntimeError(
                f"migration arm: r22-mig not migrated ({mig_report})"
            )
        code, _d, hdrs = chaos_serve._post(
            router.url, chaos_serve._session_body(b, "r22-mig")
        )
        if code != 200:
            raise RuntimeError(f"post-migration frame: {code}")
        post_mig_routed = hdrs.get("X-Routed-To")
        mig_span_names = sorted({
            ev.get("name") for ev in flight.to_dict().get("events", [])
            if ev.get("kind") == "open" and ev.get("name") in (
                "drain_migration", "drain_wait", "sessions_adopt",
                "repin",
            )
        })

        # ---- fetch + join over HTTP: the ia-synth trace --fleet path.
        with open(discovery_path) as f:
            discovery = json.load(f)

        def joined_for(rid):
            fetched = fetch_fleet_trace(discovery, rid)
            router_doc = fetched.get("router") or {}
            reps = [r["doc"]["request"]
                    for r in fetched.get("replicas") or []
                    if (r.get("doc") or {}).get("request")]
            return join_fleet_trace(
                (router_doc.get("request") if router_doc else None),
                reps, rid,
            ), fetched.get("errors") or []

        main_joined, main_errors = joined_for(main_rid)
        warm_joined, _warm_errors = joined_for(warm_rid)
        retry_joined, _retry_errors = joined_for(retry_rid)

        # ---- reconciliation: metrics fabric vs span fabric.
        from image_analogies_tpu.serving.accesslog import read_entries

        counter_retries = _counter_total(
            reg.to_dict(), "ia_route_retries_total"
        )
        span_retries = sum(
            1
            for entry in read_entries(router.access.path)
            for att in (entry.get("attempts") or [])
            if isinstance(att, dict) and att.get("retry_reason")
        )
        anomalies = fleet_watches(router.replicas(), reg)
        snap = reg.to_dict()
        record = {
            "schema_version": 1,
            "kind": "fleet_trace_load",
            "round": 22,
            "generated_by": "tools/serve_load.py --trace-out",
            "proxy_size": size,
            "config": {
                "levels": 2, "matcher": "patchmatch", "em_iters": 1,
                "pm_iters": 2, "replicas": 2,
                "shared_warm_dir": True,
            },
            "main": {
                "request_id": main_rid,
                "http_status": 200,
                "replica": main_replica,
                "client_wall_ms": round(main_wall_ms, 3),
                "fetch_errors": main_errors,
                "joined": main_joined,
            },
            "warm": {
                "request_id": warm_rid,
                "joined": warm_joined,
            },
            "retry": {
                "request_id": retry_rid,
                "http_status": 200,
                "retries": retry_joined.get("retries"),
                "retry_ms": retry_joined.get("retry_ms"),
                "routed_to": survivor,
                "joined": retry_joined,
            },
            "migration": {
                "replica": victim,
                "target": mig_report.get("migrated_to"),
                "migration_ms": mig_report.get("migration_ms"),
                "sessions": len(mig_report.get("sessions_migrated")
                                or []),
                "spans": mig_span_names,
                "post_migration_routed_to": post_mig_routed,
            },
            "overhead": {
                "pairs": len(bases),
                "base_median_ms": round(statistics.median(bases), 3),
                "min_delta_ms": round(min(deltas), 3),
                "frac": round(overhead_frac, 4),
            },
            "reconciliation": {
                "counter_retries_total": counter_retries,
                "span_retry_attempts": span_retries,
            },
            "router_metrics": {
                "requests": _counter_total(
                    snap, "ia_route_requests_total"
                ),
                "retries_total": counter_retries,
                "unrouted_total": _counter_total(
                    snap, "ia_route_unrouted_total"
                ),
            },
            "anomalies": {
                "verdict": anomalies.get("verdict"),
                "firing": anomalies.get("firing"),
            },
        }
        return record
    finally:
        gc.enable()
        router.stop()
        bare.stop()
        for p in procs:
            chaos_serve._reap(p)
        for d in (asset_dir, warm, router_dir, *states, *traces):
            shutil.rmtree(d, ignore_errors=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=None,
                    help="where to write SERVE_r13.json")
    ap.add_argument("--slo-out", default=None, metavar="PATH",
                    help="also write an SLO_r15.json SLO/critical-path "
                    "artifact from the same run (round 15)")
    ap.add_argument("--persist-out", default=None, metavar="PATH",
                    help="write a SERVE_r18.json persistent-cache + "
                    "pipelined-dispatch artifact (round 18; subprocess "
                    "restart arm + in-process pipeline arm)")
    ap.add_argument("--obs-out", default=None, metavar="PATH",
                    help="write an OBS_r19.json serving-observatory "
                    "artifact (round 19; two live replicas under a "
                    "burst, scraped + pooled over HTTP, with the "
                    "paired observatory-overhead measurement)")
    ap.add_argument("--lattice-out", default=None, metavar="PATH",
                    help="write a LATTICE_r20.json shape-lattice "
                    "artifact (round 20; lattice-on daemon vs "
                    "unbucketed reference under a never-seen-shape "
                    "burst: bounded exec keys, all-hit cold shapes, "
                    "crop bit-identity, honest bypass)")
    ap.add_argument("--router-out", default=None, metavar="PATH",
                    help="write a ROUTER_r21.json fleet-routing "
                    "artifact (round 21; subprocess replicas over a "
                    "shared warm tier behind the FleetRouter: "
                    "weak-scaling throughput, mid-burst replica add, "
                    "session affinity, embedded chaos replica-kill "
                    "arm)")
    ap.add_argument("--trace-out", default=None, metavar="PATH",
                    help="write a TRACE_r22.json fleet-trace-fabric "
                    "artifact (round 22; two subprocess replicas "
                    "behind a traced in-process router: joined cross-"
                    "process waterfall >= 95%% attributed, named retry "
                    "span reconciled with ia_route_retries_total, "
                    "visible drain migration, min-paired-delta trace "
                    "overhead)")
    ap.add_argument("--lattice-spec", default="16:36",
                    metavar="SPEC",
                    help="lattice spec for the round-20 arm "
                    "(default 16:36 — planner-chosen growth, so the "
                    "artifact records a real chosen-vs-rejected "
                    "decision)")
    ap.add_argument("--pipeline-window", type=int, default=2,
                    help="in-flight batch window for the round-18 "
                    "pipeline arm (must be > 1)")
    # Internal flags: the restart arm re-invokes this script as a
    # subprocess per phase (an in-process restart would keep jax's lru
    # caches warm and fake the cold-restart number).
    ap.add_argument("--phase", default=None,
                    choices=["persist-cold", "persist-restart"],
                    help=argparse.SUPPRESS)
    ap.add_argument("--state-dir", default=None,
                    help=argparse.SUPPRESS)
    ap.add_argument("--json-out", default=None,
                    help=argparse.SUPPRESS)
    ap.add_argument("--size", type=int, default=32,
                    help="proxy image edge (default 32)")
    ap.add_argument("--max-batch", type=int, default=2)
    ap.add_argument("--max-wait-ms", type=float, default=10.0)
    ap.add_argument("--max-queue-depth", type=int, default=3,
                    help="kept BELOW the burst client count so the "
                    "overload arm must shed")
    ap.add_argument("--clients", default="1,2,8",
                    help="comma-separated closed-loop client counts")
    ap.add_argument("--requests-per-client", type=int, default=3)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    if args.phase:
        if not (args.state_dir and args.json_out):
            print("serve_load: --phase needs --state-dir + --json-out")
            return 1
        return run_persist_phase(args)

    if not (args.out or args.persist_out or args.obs_out
            or args.lattice_out or args.router_out or args.trace_out):
        print("serve_load: need at least one of --out / --persist-out "
              "/ --obs-out / --lattice-out / --router-out / "
              "--trace-out")
        return 1

    if args.out:
        args.clients = [int(c) for c in str(args.clients).split(",")]
        if max(args.clients) <= args.max_queue_depth:
            print(
                "serve_load: largest client count must exceed "
                f"--max-queue-depth ({args.max_queue_depth}) or the "
                "overload arm cannot shed"
            )
            return 1
        record, slo_record = run_load(args)
        errs = validate_serve(record)
        if errs:
            print("serve_load: generated record INVALID:")
            for e in errs:
                print(f"  - {e}")
            return 1
        if args.slo_out:
            slo_errs = validate_slo(slo_record)
            if slo_errs:
                print("serve_load: generated SLO record INVALID:")
                for e in slo_errs:
                    print(f"  - {e}")
                return 1
        _write_json(args.out, record)
        print(
            f"serve_load: wrote {args.out} (compile saved "
            f"{record['cache']['latency_delta_ms']} ms; ledger "
            f"{record['ledger']})"
        )
        if args.slo_out:
            _write_json(args.slo_out, slo_record)
            print(
                f"serve_load: wrote {args.slo_out} (verdict "
                f"{slo_record['slo']['verdict']!r})"
            )

    if args.persist_out:
        if args.pipeline_window < 2:
            print("serve_load: --pipeline-window must be > 1")
            return 1
        persist_record = run_persist(args)
        perrs = validate_serve_persist(persist_record)
        if perrs:
            print("serve_load: generated persist record INVALID:")
            for e in perrs:
                print(f"  - {e}")
            return 1
        _write_json(args.persist_out, persist_record)
        p = persist_record["persist"]
        print(
            f"serve_load: wrote {args.persist_out} (cold "
            f"{p['cold_ms']} ms -> restart {p['cold_restart_ms']} ms, "
            f"{p['restart_speedup']}x; pipeline p99 "
            f"{persist_record['pipeline']['p99_warm_ms']} ms)"
        )

    if args.lattice_out:
        lattice_record = run_lattice(args)
        lerrs = validate_lattice(lattice_record)
        if lerrs:
            print("serve_load: generated lattice record INVALID:")
            for e in lerrs:
                print(f"  - {e}")
            return 1
        _write_json(args.lattice_out, lattice_record)
        ek = lattice_record["exec_keys"]
        print(
            f"serve_load: wrote {args.lattice_out} "
            f"({ek['bound']} buckets warm, burst added "
            f"{ek['resident_after_burst'] - ek['resident_after_warmup']}"
            f" keys, p99 cold/warm "
            f"{lattice_record['p99_cold_over_warm']}x)"
        )

    if args.router_out:
        router_record = run_router(args)
        rerrs = validate_router(router_record)
        if rerrs:
            print("serve_load: generated router record INVALID:")
            for e in rerrs:
                print(f"  - {e}")
            return 1
        _write_json(args.router_out, router_record)
        print(
            f"serve_load: wrote {args.router_out} (scaling "
            f"{router_record['scaling_factor']:.2f}x over "
            f"{router_record['fleet']['replicas']} replicas, "
            "added-replica warm ratio "
            f"{router_record['warm_start']['warm_p99_ratio']:.2f}, "
            "chaos acked_loss "
            f"{router_record['chaos']['acked_loss']})"
        )

    if args.trace_out:
        trace_record = run_fleet_trace(args)
        terrs = validate_fleet_trace(trace_record)
        if terrs:
            print("serve_load: generated fleet-trace record INVALID:")
            for e in terrs:
                print(f"  - {e}")
            return 1
        _write_json(args.trace_out, trace_record)
        mj = trace_record["main"]["joined"]
        print(
            f"serve_load: wrote {args.trace_out} (coverage "
            f"{mj['critical_path_coverage']}, skew bound "
            f"{mj['skew_bound_ms']} ms, retries "
            f"{trace_record['retry']['retries']}, migration "
            f"{trace_record['migration']['migration_ms']} ms, "
            f"overhead {trace_record['overhead']['frac']})"
        )

    if args.obs_out:
        obs_record = run_obs(args)
        oerrs = validate_obs(obs_record)
        if oerrs:
            print("serve_load: generated obs record INVALID:")
            for e in oerrs:
                print(f"  - {e}")
            return 1
        _write_json(args.obs_out, obs_record)
        fleet = obs_record["fleet"]
        print(
            f"serve_load: wrote {args.obs_out} "
            f"({fleet['replicas_live']}/{fleet['replicas_total']} "
            f"replicas, fleet verdict {fleet['slo']['verdict']!r}, "
            f"overhead {obs_record['observatory_overhead_frac']})"
        )
    return 0


def _write_json(path: str, record: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(record, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


if __name__ == "__main__":
    sys.exit(main())
