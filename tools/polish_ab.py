"""Headline A/B: jump-flooding polish vs the sequential cascade.

Round-5 decision gate for `models/patchmatch._POLISH_MODE`: at the
headline schedule (1024^2 super-resolution, 5 levels, em_iters=2,
pm_iters=6, pm_polish_iters=1) measure BOTH polish implementations'

  - steady-state wall (median of 5, device-resident inputs, scalar-
    readback barrier — bench.py's protocol), and
  - PSNR vs the exact-NN brute oracle over 3 seeds (the oracle is
    seed-independent and runs once),

plus the level-0 wall from a progress-instrumented run (the polish is
a level-0 cost).  Prints one JSON line; the winner becomes the default
and README's 'polish restructure' section quotes this run.

    python tools/polish_ab.py [size]
"""

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax.numpy as jnp

from image_analogies_tpu.utils.cache import enable_compilation_cache

enable_compilation_cache()

from image_analogies_tpu import SynthConfig, create_image_analogy, psnr
from image_analogies_tpu.utils.examples import super_resolution
from image_analogies_tpu.utils.kernelbench import sync as _sync
from image_analogies_tpu.utils.progress import ProgressWriter


def _clear_caches():
    import image_analogies_tpu.models.analogy as an

    an._level_fn_cached.cache_clear()
    an._em_step_fn.cache_clear()


def measure(mode: str, a, ap, b, size: int) -> dict:
    import image_analogies_tpu.models.patchmatch as pm

    pm._POLISH_MODE = mode
    _clear_caches()
    cfg = SynthConfig(
        levels=5, matcher="patchmatch", em_iters=2, pm_iters=6,
        pm_polish_iters=1,
    )
    run = lambda: create_image_analogy(a, ap, b, cfg)  # noqa: E731
    _sync(run())  # compile
    walls = []
    out = None
    for _ in range(5):
        t0 = time.perf_counter()
        out = run()
        _sync(out)
        walls.append(round(time.perf_counter() - t0, 4))

    # Level walls from an instrumented run (per-level sync).
    import tempfile

    fd, path = tempfile.mkstemp(suffix=".jsonl")
    os.close(fd)
    level_walls = {}
    try:
        _sync(create_image_analogy(
            a, ap, b, cfg, progress=ProgressWriter(path)
        ))
        for line in open(path):
            rec = json.loads(line)
            if rec.get("event") == "level_done":
                level_walls[rec["level"]] = rec["wall_ms"]
    finally:
        os.unlink(path)

    # PSNR over seeds vs the shared oracle.
    seeds_psnr = []
    for seed in (0, 1, 2):
        cfg_s = SynthConfig(
            levels=5, matcher="patchmatch", em_iters=2, pm_iters=6,
            pm_polish_iters=1, seed=seed,
        )
        o = np.asarray(create_image_analogy(a, ap, b, cfg_s))
        seeds_psnr.append(round(psnr(o, _ORACLE), 2))
    return {
        "mode": mode,
        "wall_median_s": statistics.median(walls),
        "wall_runs_s": walls,
        "level0_wall_ms": level_walls.get(0),
        "level_wall_ms": [level_walls[k] for k in sorted(level_walls)],
        "psnr_seeds_db": seeds_psnr,
        "psnr_min_db": min(seeds_psnr),
        "psnr_mean_db": round(float(np.mean(seeds_psnr)), 2),
    }


def main():
    global _ORACLE
    size = int(sys.argv[1]) if len(sys.argv) > 1 else 1024
    a, ap, b = super_resolution(size)
    a, ap, b = (jnp.asarray(x, jnp.float32) for x in (a, ap, b))
    for x in (a, ap, b):
        _sync(x)
    # Exact oracle, once (seed-independent); cached on disk by
    # tools/full_oracle.py naming if available.
    opath = os.path.join(
        os.path.dirname(__file__), "_oracle_out", f"oracle_f32_{size}.npy"
    )
    if os.path.exists(opath):
        _ORACLE = np.load(opath)
    else:
        _ORACLE = np.asarray(create_image_analogy(
            a, ap, b, SynthConfig(levels=5, matcher="brute", em_iters=2)
        ))
    res = {
        "size": size,
        "jump": measure("jump", a, ap, b, size),
        "sequential": measure("sequential", a, ap, b, size),
    }
    j, s = res["jump"], res["sequential"]
    res["delta"] = {
        "wall_s": round(j["wall_median_s"] - s["wall_median_s"], 4),
        "level0_ms": (
            round(j["level0_wall_ms"] - s["level0_wall_ms"], 1)
            if j["level0_wall_ms"] and s["level0_wall_ms"] else None
        ),
        "psnr_min_db": round(j["psnr_min_db"] - s["psnr_min_db"], 2),
    }
    print(json.dumps(res), flush=True)


if __name__ == "__main__":
    main()
