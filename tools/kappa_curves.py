"""PSNR-vs-kappa curves: kernel PatchMatch path vs the kappa-aware brute
oracle (VERDICT r3 task 3; r4 weak 5 adds the NPR content family).

Runs a content pair for kappa in {0, 2, 5}, measuring PSNR of the
kernel-path output against the CoherenceWrapper(brute) oracle — the
exact acceptance metric BENCH's configs 2/5 use.  Prints one JSON
line; run on the TPU backend.

    python tools/kappa_curves.py 512            # artistic_filter
    python tools/kappa_curves.py 1024 npr       # config 5's own
                                                # content family/scale
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax.numpy as jnp

from image_analogies_tpu.utils.cache import enable_compilation_cache

enable_compilation_cache()

from image_analogies_tpu import SynthConfig, create_image_analogy, psnr
from image_analogies_tpu.utils.examples import artistic_filter, npr_frames


def main(size: int = 512, family: str = "artistic"):
    if family == "npr":
        # Config 5's own content: the style pair + ONE representative
        # frame of the NPR stack (the batch runner's per-frame synthesis
        # is exactly this computation; kappa acts per frame).
        a_h, ap_h, frames = npr_frames(n_frames=1, size=size)
        b_h = np.asarray(frames)[0]
    else:
        a_h, ap_h, b_h = artistic_filter(size)
    a = jnp.asarray(a_h, jnp.float32)
    ap = jnp.asarray(ap_h, jnp.float32)
    b = jnp.asarray(b_h, jnp.float32)

    rows = []
    for kappa in (0.0, 2.0, 5.0):
        kw = dict(levels=5, em_iters=2, kappa=kappa)
        oracle = np.asarray(
            create_image_analogy(
                a, ap, b, SynthConfig(matcher="brute", **kw)
            )
        )
        t0 = time.perf_counter()
        out = np.asarray(
            create_image_analogy(
                a, ap, b, SynthConfig(matcher="patchmatch", **kw)
            )
        )
        rows.append(
            {
                "kappa": kappa,
                "psnr_vs_oracle_db": round(psnr(out, oracle), 2),
                "wall_s": round(time.perf_counter() - t0, 3),
            }
        )
    print(json.dumps({"size": size, "family": family, "curves": rows}))


if __name__ == "__main__":
    main(
        int(sys.argv[1]) if len(sys.argv) > 1 else 512,
        sys.argv[2] if len(sys.argv) > 2 else "artistic",
    )
