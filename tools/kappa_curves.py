"""PSNR-vs-kappa curves: kernel PatchMatch path vs the kappa-aware brute
oracle (VERDICT r3 task 3).

Runs the artistic-filter pair at 512^2 for kappa in {0, 2, 5}, measuring
PSNR of the kernel-path output against the CoherenceWrapper(brute)
oracle — the exact acceptance metric BENCH's configs 2/5 use.  Prints
one JSON line; run on the TPU backend.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax.numpy as jnp

from image_analogies_tpu.utils.cache import enable_compilation_cache

enable_compilation_cache()

from image_analogies_tpu import SynthConfig, create_image_analogy, psnr
from image_analogies_tpu.utils.examples import artistic_filter


def main(size: int = 512):
    a_h, ap_h, b_h = artistic_filter(size)
    a = jnp.asarray(a_h, jnp.float32)
    ap = jnp.asarray(ap_h, jnp.float32)
    b = jnp.asarray(b_h, jnp.float32)

    rows = []
    for kappa in (0.0, 2.0, 5.0):
        kw = dict(levels=5, em_iters=2, kappa=kappa)
        oracle = np.asarray(
            create_image_analogy(
                a, ap, b, SynthConfig(matcher="brute", **kw)
            )
        )
        t0 = time.perf_counter()
        out = np.asarray(
            create_image_analogy(
                a, ap, b, SynthConfig(matcher="patchmatch", **kw)
            )
        )
        rows.append(
            {
                "kappa": kappa,
                "psnr_vs_oracle_db": round(psnr(out, oracle), 2),
                "wall_s": round(time.perf_counter() - t0, 3),
            }
        )
    print(json.dumps({"size": size, "curves": rows}))


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 512)
