#!/usr/bin/env python
"""Validate a ROUTER_r21.json fleet-routing artifact (round 21).

The fleet-router acceptance bar, held by arithmetic: the committed
record must show

  * a >= 3-replica routed fleet whose measured throughput under the
    stated weak-scaling protocol (one closed-loop client per replica,
    the replicas' own batching policy — NOT a per-request benchmark)
    scales >= 1.6x over the single-replica baseline ON THE SAME BOX,
    with `scaling_factor` re-derived here from the two throughput
    cells;
  * a replica ADDED MID-BURST over the shared warm tier (round-18
    disk executable cache + round-20 observed-warmup union under the
    common --warm-dir) whose FIRST routed request lands within 2x the
    fleet's warm p99 — the cold-start number a fresh replica would
    otherwise pay is seconds of XLA compile, so a ratio <= 2.0 is the
    proof the warm tier actually engaged;
  * session affinity with a 100% hit rate for non-draining replicas:
    every sessioned request after a session's first must be a HIT
    (`hit == expected_hits`, `repin == 0`) — a single silent re-pin
    would cold-start a video stream mid-sequence;
  * the embedded chaos replica-kill arm (tools/chaos_serve.py
    `arm_replica_kill_midburst`): zero acked loss, bit-identical
    journal replay on the --takeover successor, at least one session
    MIGRATED off the drained replica, and the migrated session's next
    frame bit-identical to the no-migration reference.

Usage:
    python tools/check_router.py ROUTER_r21.json

Runs under pytest too (tests/test_router.py validates the COMMITTED
artifact) so tier-1 fails if the record is missing, truncated, or any
fleet claim stops reproducing.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_HERE)
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

ROUTER_SCHEMA_VERSION = 1
MIN_FLEET_REPLICAS = 3
MIN_SCALING_FACTOR = 1.6
MAX_WARM_P99_RATIO = 2.0
_REL = 1e-6


def _num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _pos(v) -> bool:
    return _num(v) and v > 0


def _close(a, b) -> bool:
    return abs(a - b) <= _REL * max(abs(a), abs(b), 1.0)


def _validate_phase(phase, name: str, errs: List[str]) -> None:
    if not isinstance(phase, dict):
        errs.append(f"{name}: missing or not an object")
        return
    for key in ("replicas", "requests", "wall_s", "throughput_rps",
                "p50_ms", "p99_ms"):
        if not _pos(phase.get(key)):
            errs.append(f"{name}.{key}: not a positive number "
                        f"({phase.get(key)!r})")
    wall, n, thr = (phase.get("wall_s"), phase.get("requests"),
                    phase.get("throughput_rps"))
    if _pos(wall) and _pos(n) and _pos(thr) and not _close(thr, n / wall):
        errs.append(
            f"{name}.throughput_rps {thr} != requests/wall_s "
            f"{n / wall} (re-derived)"
        )


def validate_router(record: dict) -> List[str]:
    errs: List[str] = []
    if record.get("schema_version") != ROUTER_SCHEMA_VERSION:
        errs.append(
            f"schema_version {record.get('schema_version')!r} != "
            f"{ROUTER_SCHEMA_VERSION}"
        )
    if record.get("kind") != "router":
        errs.append(f"kind {record.get('kind')!r} != 'router'")

    proto = record.get("protocol") or {}
    if proto.get("mode") != "weak_scaling":
        errs.append(
            f"protocol.mode {proto.get('mode')!r} != 'weak_scaling' "
            "(the scaling claim is only honest under the stated "
            "closed-loop-client-per-replica protocol)"
        )
    if proto.get("clients_per_replica") != 1:
        errs.append(
            "protocol.clients_per_replica "
            f"{proto.get('clients_per_replica')!r} != 1"
        )

    single = record.get("single")
    fleet = record.get("fleet")
    _validate_phase(single, "single", errs)
    _validate_phase(fleet, "fleet", errs)
    if isinstance(single, dict) and single.get("replicas") != 1:
        errs.append(f"single.replicas {single.get('replicas')!r} != 1")
    if isinstance(fleet, dict):
        nrep = fleet.get("replicas")
        if not (_num(nrep) and nrep >= MIN_FLEET_REPLICAS):
            errs.append(
                f"fleet.replicas {nrep!r} < {MIN_FLEET_REPLICAS}"
            )
        spread = fleet.get("per_replica_requests")
        if not (isinstance(spread, dict) and spread):
            errs.append("fleet.per_replica_requests: missing")
        elif _num(fleet.get("requests")):
            served = sum(v for v in spread.values() if _num(v))
            if served < fleet["requests"]:
                errs.append(
                    f"fleet.per_replica_requests sums to {served} < "
                    f"fleet.requests {fleet['requests']} (requests "
                    "unaccounted for)"
                )
            if any(not _pos(v) for v in spread.values()):
                errs.append(
                    "fleet.per_replica_requests: a replica served 0 "
                    "requests — the router did not spread the load"
                )

    scaling = record.get("scaling_factor")
    if not _pos(scaling):
        errs.append(f"scaling_factor {scaling!r}: not a number")
    else:
        if (isinstance(single, dict) and isinstance(fleet, dict)
                and _pos(single.get("throughput_rps"))
                and _pos(fleet.get("throughput_rps"))):
            derived = (fleet["throughput_rps"]
                       / single["throughput_rps"])
            if not _close(scaling, derived):
                errs.append(
                    f"scaling_factor {scaling} != fleet/single "
                    f"throughput {derived} (re-derived)"
                )
        if scaling < MIN_SCALING_FACTOR:
            errs.append(
                f"scaling_factor {scaling:.3f} < {MIN_SCALING_FACTOR} "
                "(fleet does not beat one replica by the bar)"
            )

    warm = record.get("warm_start") or {}
    first = warm.get("first_request_ms")
    p99 = warm.get("fleet_warm_p99_ms")
    ratio = warm.get("warm_p99_ratio")
    if not _pos(first):
        errs.append(f"warm_start.first_request_ms {first!r}")
    if not _pos(p99):
        errs.append(f"warm_start.fleet_warm_p99_ms {p99!r}")
    if not _pos(ratio):
        errs.append(f"warm_start.warm_p99_ratio {ratio!r}")
    elif _pos(first) and _pos(p99):
        if not _close(ratio, first / p99):
            errs.append(
                f"warm_start.warm_p99_ratio {ratio} != "
                f"first/fleet_p99 {first / p99} (re-derived)"
            )
        if ratio > MAX_WARM_P99_RATIO:
            errs.append(
                f"warm_start.warm_p99_ratio {ratio:.3f} > "
                f"{MAX_WARM_P99_RATIO} (mid-burst replica did not "
                "start warm — shared warm tier not engaged)"
            )

    aff = record.get("affinity") or {}
    for key in ("sessions", "hit", "new", "expected_hits"):
        if not _num(aff.get(key)):
            errs.append(f"affinity.{key} {aff.get(key)!r}: not a number")
    if _num(aff.get("hit")) and _num(aff.get("expected_hits")):
        if aff["hit"] != aff["expected_hits"]:
            errs.append(
                f"affinity.hit {aff['hit']} != expected_hits "
                f"{aff['expected_hits']} (a sessioned request missed "
                "its pinned replica)"
            )
    if aff.get("repin") != 0:
        errs.append(
            f"affinity.repin {aff.get('repin')!r} != 0 (a session was "
            "re-pinned off a live, non-draining replica)"
        )
    if aff.get("hit_rate") != 1.0:
        errs.append(
            f"affinity.hit_rate {aff.get('hit_rate')!r} != 1.0"
        )

    chaos = record.get("chaos") or {}
    if chaos.get("name") != "replica_kill_midburst":
        errs.append(
            f"chaos.name {chaos.get('name')!r} != "
            "'replica_kill_midburst'"
        )
    if chaos.get("acked_loss") != 0:
        errs.append(
            f"chaos.acked_loss {chaos.get('acked_loss')!r} != 0 "
            "(acked requests were lost across the replica kill)"
        )
    if chaos.get("replay_bit_identical") is not True:
        errs.append("chaos.replay_bit_identical is not true")
    if not (_num(chaos.get("sessions_migrated"))
            and chaos["sessions_migrated"] >= 1):
        errs.append(
            f"chaos.sessions_migrated {chaos.get('sessions_migrated')!r}"
            " < 1 (rolling restart migrated no sessions)"
        )
    if chaos.get("migrated_frame_bit_identical") is not True:
        errs.append(
            "chaos.migrated_frame_bit_identical is not true (the "
            "migrated session's next frame diverged from the "
            "no-migration reference)"
        )
    if (_num(chaos.get("routed_burst")) and _num(chaos.get(
            "routed_served"))
            and chaos["routed_served"] < chaos["routed_burst"]):
        errs.append(
            f"chaos.routed_served {chaos['routed_served']} < "
            f"routed_burst {chaos['routed_burst']} (a live routed "
            "client was dropped during the kill)"
        )
    return errs


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("record", help="path to ROUTER_r21.json")
    args = ap.parse_args(argv)
    try:
        with open(args.record, "r", encoding="utf-8") as fh:
            record = json.load(fh)
    except (OSError, ValueError) as e:
        print(f"check_router: cannot read {args.record}: {e}",
              file=sys.stderr)
        return 2
    errs = validate_router(record)
    if errs:
        print(f"check_router: {args.record}: {len(errs)} violation(s):")
        for e in errs:
            print(f"  - {e}")
        return 1
    fleet = record.get("fleet") or {}
    print(
        f"check_router: {args.record} OK — {fleet.get('replicas')} "
        f"replicas, scaling {record.get('scaling_factor'):.2f}x, "
        f"added-replica warm ratio "
        f"{(record.get('warm_start') or {}).get('warm_p99_ratio'):.2f}, "
        f"chaos acked_loss {(record.get('chaos') or {}).get('acked_loss')}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
