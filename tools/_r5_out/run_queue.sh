#!/bin/bash
# Round-5 post-oracle TPU measurement queue.  Waits for the 4096^2
# oracle wrapper to exit, verifies no TPU client is alive (ONE client
# at a time through the axon tunnel — see the wedge post-mortem in
# README), then runs each measurement as its own process, sequentially,
# with a hard timeout per step so one hang cannot starve the rest.
cd /root/repo
out=tools/_r5_out
log=$out/queue.log
mkdir -p $out

step() {  # step <name> <timeout-secs> <cmd...>
  name=$1; secs=$2; shift 2
  echo "=== $name start $(date)" >> $log
  timeout -k 30 $secs "$@" > $out/$name.log 2>&1
  rc=$?
  echo "=== $name done rc=$rc $(date)" >> $log
  sleep 30  # let the client tear down before the next one attaches
}

echo "=== queue waiting for oracle wrapper $(date)" >> $log
while ps -p "$(cat $out/oracle_wrapper_pid 2>/dev/null || echo 0)" > /dev/null 2>&1; do
  sleep 60
done
# Belt and braces: no python TPU client may be alive.  Match the
# INVOCATION (python + script path), not bare names: the session
# driver's own cmdline carries strings like "bench.py" in its prompt
# text and a bare-name grep waits on it forever (hit 2026-08-01).
_clients() {
  ps aux \
    | grep -E "python[0-9.]* (tools/(full_oracle|scale_bench|polish_ab|kappa_curves)\.py|bench\.py)" \
    | grep -v grep
}
while _clients > /dev/null; do
  echo "=== queue: client still alive, waiting $(date)" >> $log
  sleep 60
done
sleep 30
echo "=== queue starting $(date)" >> $log

step polish_ab   2700 python tools/polish_ab.py 1024
step kappa_npr   5400 python tools/kappa_curves.py 1024 npr
# New-schedule PM outputs vs the cached exact oracles: drop the PM
# caches so full_oracle re-synthesizes with the size-aware schedule,
# reusing the (schedule-independent) oracle .npy.
rm -f tools/_oracle_out/pm_3072.npy tools/_oracle_out/pm_3072.json
rm -rf tools/_oracle_out/pm_3072.ckpt
rm -f tools/_oracle_out/pm_4096.npy tools/_oracle_out/pm_4096.json
rm -rf tools/_oracle_out/pm_4096.ckpt
step oracle_3072_newpm 3600 python tools/full_oracle.py 3072
step oracle_4096_newpm 5400 python tools/full_oracle.py 4096
step scale_rows  9000 python tools/scale_bench.py 4096
step bench_a     3600 python bench.py
step bench_b     3600 python bench.py
touch $out/QUEUE_DONE
echo "=== queue complete $(date)" >> $log
