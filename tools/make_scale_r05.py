"""Assemble SCALE_r05.json from the round-5 queue's outputs.

Inputs (all produced by tools/_r5_out/run_queue.sh):
  tools/_r5_out/scale_rows.log          scale_bench rows (1024/2048/4096)
  tools/_r5_out/oracle_3072_newpm.log   full_oracle line: new-schedule PM
                                        vs the cached 3072^2 exact oracle
  tools/_r5_out/oracle_4096_newpm.log   same at 4096^2
  tools/_oracle_out/run_4096_r5.log     (fallback) the oracle run's own
                                        final line: OLD-schedule PM PSNR

Every row <= 2048^2 carries scale_bench's own full-oracle PSNR; the
3072^2 row is built from the full_oracle line (no scale_bench row at
that size); the 4096^2 row takes its PSNR from the full_oracle rerun.

Usage: python tools/make_scale_r05.py [out.json]
"""

import json
import os
import sys

_OUT = os.path.join(os.path.dirname(__file__), "_r5_out")

COMMENT = (
    "Large-image scaling rows, TPU v5e-1, 2026-08-01, round 5: "
    "size-aware search schedule (pm sweeps +2 past a 4M-px A domain, "
    "models/patchmatch._pm_iters_for) with the sequential polish "
    "cascade (the jump-flood restructure measured worse on both axes "
    "and is non-default; tools/polish_ab.py).  Quality: EVERY row >= "
    "1024^2 carries PSNR vs a "
    "FULL-SYNTHESIS exact-NN oracle — f32-table brute to 2048^2; at "
    "3072^2 the pure lean-brute bf16-table oracle; at 4096^2 the "
    "default-budget brute oracle (exact f32 tables at the sub-wall "
    "coarse levels, bf16 lean-brute at levels 1-0 — the finest levels, "
    "which dominate the final image, match in the same bf16 lean "
    "metric the production path uses; per-row oracle_kind records "
    "this) — plus the "
    "stratified-jittered exact probe (1M px, bootstrap 95% CI on the "
    "achieved/exact mean-distance ratio) at scale_bench sizes.  The "
    "3072^2/4096^2 oracle outputs were computed once (checkpointed, "
    "resumable; tools/full_oracle.py) and PM is re-compared against "
    "the cached oracle .npy after schedule changes."
)


def _last_json(path: str):
    row = None
    if not os.path.exists(path):
        return None
    for line in open(path):
        line = line.strip()
        if line.startswith("{"):
            try:
                row = json.loads(line)
            except ValueError:
                continue
    return row


def main():
    out_path = sys.argv[1] if len(sys.argv) > 1 else "SCALE_r05.json"
    rows = {}
    scale_log = os.path.join(_OUT, "scale_rows.log")
    if os.path.exists(scale_log):
        for line in open(scale_log):
            line = line.strip()
            if line.startswith("{") and '"size"' in line:
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if "size" in row:
                    rows[row["size"]] = row

    for size in (3072, 4096):
        schedule = "r5-size-aware"
        oline = _last_json(os.path.join(_OUT, f"oracle_{size}_newpm.log"))
        if oline is None and size == 4096:
            # Fallback: the oracle run's own final line — its PM side is
            # the PRE-schedule-change cache, so the row must say so.
            oline = _last_json(
                os.path.join(
                    os.path.dirname(__file__), "_oracle_out",
                    "run_4096_r5.log",
                )
            )
            schedule = "pre-r5 (flat pm_iters)"
        if oline is None or "psnr_vs_full_oracle_db" not in oline:
            print(
                f"WARNING: no full-oracle PSNR line for {size} — row "
                "ships without it", file=sys.stderr,
            )
            continue
        row = rows.setdefault(size, {"size": size})
        row["psnr_vs_full_oracle_db"] = oline["psnr_vs_full_oracle_db"]
        row["oracle_kind"] = oline["oracle"]
        row["oracle_wall_s"] = oline["oracle_wall_s"]
        row["pm_fresh_process_wall_s"] = oline["pm_wall_s"]
        row["pm_schedule"] = schedule

    assert rows, "no rows found — did the queue run?"
    for size in (3072, 4096):
        assert "psnr_vs_full_oracle_db" in rows.get(size, {}), (
            f"the {size} row lacks its full-oracle PSNR — the artifact "
            "comment would misdescribe it; fix the inputs or the comment"
        )
    with open(out_path, "w") as f:
        json.dump(
            {"comment": COMMENT, "rows": [rows[k] for k in sorted(rows)]},
            f, indent=1,
        )
        f.write("\n")
    print(f"wrote {out_path} with sizes {sorted(rows)}")


if __name__ == "__main__":
    main()
