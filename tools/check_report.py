#!/usr/bin/env python
"""Validate a telemetry `report.json` (telemetry/report.py schema) or a
run-sentinel `health.json` (telemetry/sentinel.py schema).

Fast, dependency-free smoke check for traced runs: exits nonzero when
the report is structurally broken or missing phases — an unknown
schema version, no `levels`, a level without `wall_ms`/`shape`/
`nnf_energy`, a gap in the level sequence, or a missing `prologue`
phase.  `device_busy_ms` may be null (a CPU/tunnelled backend forwards
no accelerator planes) but the KEY must exist: the report's contract
is to state what it measured, never to omit the question.

A record with `"kind": "health"` dispatches to `validate_health`
(round 9): the verdict must be consistent with its checks, every
non-skipped check must state both `expected` and `observed`, and every
check must carry the measured-vs-carried/modeled provenance field —
a verdict computed over carried cells has to say so.

A record with `"kind": "flight"` dispatches to `validate_flight`
(round 10): the flight recorder's post-mortem dump
(telemetry/flight.py — the artifact a SIGTERM'd/crashed run leaves)
must carry a known flush reason, a well-formed bounded event window
with a consistent drop count, and a metrics section that is either
null or a registry exposition object.

Usage:
    python tools/check_report.py path/to/report.json
    python tools/check_report.py path/to/health.json   # auto-detected
    python tools/check_report.py path/to/flight.json   # auto-detected
    python tools/check_report.py --no-prologue report.json  # resumed
        runs skip the prologue span; relax that requirement only

Runs under pytest too (tests/test_telemetry.py wraps both validators)
so tier-1 exercises the same rules the CLI tool enforces.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List

SCHEMA_VERSION = 1
HEALTH_SCHEMA_VERSION = 1
FLIGHT_SCHEMA_VERSION = 1

_FLIGHT_REASONS = (
    "sigterm", "sigint", "atexit", "violation", "watchdog",
    "session-end", "manual", "drain", "incident",
)
_FLIGHT_EVENT_KINDS = ("open", "close", "mark")

_LEVEL_REQUIRED = ("level", "shape", "wall_ms", "nnf_energy",
                   "device_busy_ms")

_HEALTH_STATUSES = ("ok", "degraded", "violated", "skipped")
_HEALTH_VERDICTS = ("ok", "degraded", "violated")
_HEALTH_PROVENANCES = ("measured", "carried", "modeled")
# violated > degraded > ok; skipped never moves the verdict.
_SEVERITY = {"skipped": 0, "ok": 0, "degraded": 1, "violated": 2}


def validate_health(health: dict) -> List[str]:
    """Violations in a telemetry/sentinel.py health.json (empty list =
    valid)."""
    errs: List[str] = []
    if not isinstance(health, dict):
        return ["health record is not a JSON object"]
    if health.get("schema_version") != HEALTH_SCHEMA_VERSION:
        errs.append(
            f"schema_version {health.get('schema_version')!r} != "
            f"{HEALTH_SCHEMA_VERSION}"
        )
    if health.get("kind") != "health":
        errs.append(f"kind {health.get('kind')!r} != 'health'")
    verdict = health.get("verdict")
    if verdict not in _HEALTH_VERDICTS:
        errs.append(f"verdict {verdict!r} names none of {_HEALTH_VERDICTS}")

    checks = health.get("checks")
    if not isinstance(checks, list) or not checks:
        errs.append("checks: missing or empty")
        checks = []
    worst = 0
    for i, c in enumerate(checks):
        if not isinstance(c, dict) or not isinstance(c.get("name"), str):
            errs.append(f"checks[{i}]: not a named check object")
            continue
        status = c.get("status")
        if status not in _HEALTH_STATUSES:
            errs.append(
                f"checks[{i}] ({c['name']}): status {status!r} names "
                f"none of {_HEALTH_STATUSES}"
            )
            continue
        worst = max(worst, _SEVERITY[status])
        # The measured-vs-carried/modeled provenance field: a verdict
        # over carried or projected cells must say so on every check.
        if c.get("provenance") not in _HEALTH_PROVENANCES:
            errs.append(
                f"checks[{i}] ({c['name']}): provenance "
                f"{c.get('provenance')!r} names none of "
                f"{_HEALTH_PROVENANCES}"
            )
        if status != "skipped":
            for key in ("expected", "observed"):
                if key not in c:
                    errs.append(
                        f"checks[{i}] ({c['name']}): non-skipped check "
                        f"missing key {key!r}"
                    )
        if not isinstance(c.get("detail"), str):
            errs.append(
                f"checks[{i}] ({c['name']}): detail is not a string"
            )
    if checks and verdict in _HEALTH_VERDICTS:
        want = {0: "ok", 1: "degraded", 2: "violated"}[worst]
        if verdict != want:
            errs.append(
                f"verdict {verdict!r} inconsistent with its checks "
                f"(worst status implies {want!r})"
            )
    counts = health.get("counts")
    if not isinstance(counts, dict):
        errs.append("counts: missing section")
    elif checks:
        for s in _HEALTH_STATUSES:
            n = len([c for c in checks
                     if isinstance(c, dict) and c.get("status") == s])
            if counts.get(s) != n:
                errs.append(
                    f"counts[{s!r}] {counts.get(s)!r} != {n} checks"
                )
    return errs


def validate_flight(flight: dict) -> List[str]:
    """Violations in a telemetry/flight.py flight.json (empty list =
    valid).  The dump is the artifact of LAST resort — written from
    signal handlers and atexit callbacks — so the validator holds it
    to the full schema: a recorder that starts writing half-dumps must
    fail tier-1, not be discovered during a real post-mortem."""
    errs: List[str] = []
    if not isinstance(flight, dict):
        return ["flight record is not a JSON object"]
    if flight.get("schema_version") != FLIGHT_SCHEMA_VERSION:
        errs.append(
            f"schema_version {flight.get('schema_version')!r} != "
            f"{FLIGHT_SCHEMA_VERSION}"
        )
    if flight.get("kind") != "flight":
        errs.append(f"kind {flight.get('kind')!r} != 'flight'")
    reason = flight.get("flushed_on")
    if reason not in _FLIGHT_REASONS:
        errs.append(
            f"flushed_on {reason!r} names none of {_FLIGHT_REASONS}"
        )
    if not isinstance(flight.get("ts"), str):
        errs.append("ts: missing ISO-8601 flush timestamp")

    events = flight.get("events")
    if not isinstance(events, list):
        errs.append("events: missing list")
        events = []
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            errs.append(f"events[{i}]: not an object")
            continue
        if ev.get("kind") not in _FLIGHT_EVENT_KINDS:
            errs.append(
                f"events[{i}]: kind {ev.get('kind')!r} names none of "
                f"{_FLIGHT_EVENT_KINDS}"
            )
        if not isinstance(ev.get("name"), str):
            errs.append(f"events[{i}]: name is not a string")
        if not isinstance(ev.get("t"), (int, float)):
            errs.append(f"events[{i}]: t is not a number")

    for key in ("capacity", "n_events_total", "dropped_events",
                "n_flushes"):
        v = flight.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            errs.append(f"{key}: {v!r} is not a non-negative int")
    n_total = flight.get("n_events_total")
    dropped = flight.get("dropped_events")
    if isinstance(n_total, int) and isinstance(dropped, int):
        if n_total - dropped != len(events):
            errs.append(
                f"event accounting: n_events_total {n_total} - "
                f"dropped_events {dropped} != {len(events)} events "
                "in the window"
            )

    if not isinstance(flight.get("span_stack"), list):
        errs.append("span_stack: missing list")
    snapshots = flight.get("snapshots")
    if not isinstance(snapshots, list):
        errs.append("snapshots: missing list")
    else:
        for i, sn in enumerate(snapshots):
            if not isinstance(sn, dict) or not isinstance(
                sn.get("metrics"), dict
            ):
                errs.append(
                    f"snapshots[{i}]: not a metrics snapshot object"
                )
    metrics = flight.get("metrics")
    if metrics is not None and not isinstance(metrics, dict):
        errs.append("metrics: neither null nor a registry exposition")
    return errs


def validate_report(report: dict, require_prologue: bool = True
                    ) -> List[str]:
    """Return a list of violations (empty = valid)."""
    errs: List[str] = []
    if not isinstance(report, dict):
        return ["report is not a JSON object"]
    if report.get("schema_version") != SCHEMA_VERSION:
        errs.append(
            f"schema_version {report.get('schema_version')!r} != "
            f"{SCHEMA_VERSION}"
        )

    levels = report.get("levels")
    if not isinstance(levels, list) or not levels:
        errs.append("levels: missing or empty")
        levels = []
    seen = []
    for i, lv in enumerate(levels):
        if not isinstance(lv, dict):
            errs.append(f"levels[{i}]: not an object")
            continue
        for key in _LEVEL_REQUIRED:
            if key not in lv:
                errs.append(f"levels[{i}]: missing key {key!r}")
        if not isinstance(lv.get("level"), int):
            errs.append(f"levels[{i}]: level is not an int")
            continue
        seen.append(lv["level"])
        wall = lv.get("wall_ms")
        if not isinstance(wall, (int, float)) or wall <= 0:
            errs.append(
                f"levels[{i}] (level {lv['level']}): wall_ms {wall!r} "
                "is not a positive number"
            )
        shape = lv.get("shape")
        if shape is not None and (
            not isinstance(shape, list) or len(shape) != 2
        ):
            errs.append(
                f"levels[{i}] (level {lv['level']}): shape {shape!r} "
                "is not [h, w]"
            )
        dev = lv.get("device_busy_ms")
        if dev is not None and not isinstance(dev, (int, float)):
            errs.append(
                f"levels[{i}] (level {lv['level']}): device_busy_ms "
                f"{dev!r} is neither null nor a number"
            )
    if seen:
        # The pyramid runs coarse -> fine and ends at level 0; any gap
        # means a phase's span was dropped on the floor.
        expected = list(range(max(seen), -1, -1))
        if seen != expected:
            errs.append(
                f"levels: indices {seen} are not the contiguous "
                f"coarse-to-fine sequence {expected}"
            )

    prologue = report.get("prologue")
    if require_prologue:
        if not isinstance(prologue, dict):
            errs.append("prologue: missing phase")
        else:
            if not isinstance(prologue.get("wall_ms"), (int, float)):
                errs.append("prologue: wall_ms is not a number")
            if "device_busy_ms" not in prologue:
                errs.append("prologue: missing key 'device_busy_ms'")

    run = report.get("run")
    if run is not None and not isinstance(run, dict):
        errs.append("run: not an object")

    device = report.get("device")
    if not isinstance(device, dict):
        errs.append("device: missing section")
    elif "total_busy_ms" not in device:
        errs.append("device: missing key 'total_busy_ms'")
    return errs


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("report", help="path to report.json")
    ap.add_argument(
        "--no-prologue", action="store_true",
        help="don't require the prologue phase (resumed runs skip it)",
    )
    args = ap.parse_args(argv)
    try:
        with open(args.report) as f:
            report = json.load(f)
    except (OSError, ValueError) as e:
        print(f"check_report: cannot read {args.report}: {e}",
              file=sys.stderr)
        return 2
    if isinstance(report, dict) and report.get("kind") == "flight":
        errs = validate_flight(report)
        if errs:
            for e in errs:
                print(f"check_report: {e}", file=sys.stderr)
            print(
                f"check_report: FAIL — {len(errs)} violation(s) in "
                f"{args.report}", file=sys.stderr,
            )
            return 1
        print(
            f"check_report: OK — flight dump "
            f"(flushed_on={report.get('flushed_on')!r}, "
            f"{len(report.get('events', []))} event(s), "
            f"{report.get('dropped_events')} dropped)"
        )
        return 0
    if isinstance(report, dict) and report.get("kind") == "health":
        errs = validate_health(report)
        if errs:
            for e in errs:
                print(f"check_report: {e}", file=sys.stderr)
            print(
                f"check_report: FAIL — {len(errs)} violation(s) in "
                f"{args.report}", file=sys.stderr,
            )
            return 1
        if report.get("verdict") == "violated":
            # Schema-valid, but the run failed its own assertions —
            # a gate built on this tool must agree with `ia-synth
            # health` and check_bench, which both refuse the verdict.
            print(
                f"check_report: FAIL — {args.report} is well-formed "
                "but its verdict is 'violated' (the run failed its "
                "expected-vs-observed checks)", file=sys.stderr,
            )
            return 1
        print(
            f"check_report: OK — health verdict "
            f"{report.get('verdict')!r}, "
            f"{len(report.get('checks', []))} check(s)"
        )
        return 0
    errs = validate_report(report, require_prologue=not args.no_prologue)
    if errs:
        for e in errs:
            print(f"check_report: {e}", file=sys.stderr)
        print(
            f"check_report: FAIL — {len(errs)} violation(s) in "
            f"{args.report}", file=sys.stderr,
        )
        return 1
    n = len(report.get("levels", []))
    print(f"check_report: OK — {n} level(s), schema v{SCHEMA_VERSION}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
