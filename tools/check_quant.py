#!/usr/bin/env python
"""Validate a QUANT_r11.json round artifact (the compressed-candidate
pipeline decision record) — the tools/check_polish.py discipline
applied to the round-11 artifact, so the acceptance criteria ("a
measured default-path bit-identity cell, per-arm proxy quality pins
inside the dist-ratio/PSNR gates, the extended byte model with its
>= 3x modeled reduction at 1024^2, a pre-stated kill criterion, and
the hardware A/B recipe") are enforced by a validator instead of
trusted to prose.

Usage:
    python tools/check_quant.py QUANT_r11.json

Runs under pytest too (tests/test_check_bench.py TestCheckQuant
validates the COMMITTED artifact) so tier-1 fails if the record is
missing, truncated, or structurally degraded.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List

_CAND_DTYPES = ("bf16", "int8")
_DIST_RATIO_MAX = 1.80
_PSNR_MIN_DB = 35.0
# The tentpole's acceptance floor: modeled candidate-DMA bytes/sweep
# at 1024^2 on the compressed path, vs the round-7 packed baseline.
_MIN_BYTES_RATIO = 3.0


def _num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def validate_quant(record: dict) -> List[str]:
    """Return a list of violations (empty = valid)."""
    errs: List[str] = []
    if not isinstance(record, dict):
        return ["record is not a JSON object"]

    dec = record.get("decision")
    if not isinstance(dec, dict):
        errs.append("decision: missing object")
        dec = {}
    if dec.get("default_cand_dtype") not in _CAND_DTYPES:
        errs.append(
            f"decision.default_cand_dtype "
            f"{dec.get('default_cand_dtype')!r} names none of "
            f"{_CAND_DTYPES}"
        )
    dp = dec.get("default_pca_prune")
    if not isinstance(dp, str) or not dp.strip():
        errs.append("decision.default_pca_prune: missing/empty")
    if not isinstance(dec.get("kill_criterion_prestated"), str) or not (
        dec.get("kill_criterion_prestated") or ""
    ).strip():
        errs.append("decision.kill_criterion_prestated: missing/empty")

    meas = record.get("measured_this_round")
    if not isinstance(meas, dict):
        errs.append("measured_this_round: missing object")
        meas = {}
    if meas.get("default_bit_identical") is not True:
        errs.append(
            "measured_this_round.default_bit_identical must be true — "
            "the bf16/prune-off path must reproduce today's graphs "
            "byte-for-byte"
        )
    arms = meas.get("arms")
    if not isinstance(arms, list) or len(arms) < 2:
        errs.append(
            "measured_this_round.arms: need the baseline plus at "
            "least one compressed arm"
        )
        arms = []
    for i, arm in enumerate(arms):
        if not isinstance(arm, dict):
            errs.append(f"arms[{i}]: not an object")
            continue
        if arm.get("cand_dtype") not in _CAND_DTYPES:
            errs.append(
                f"arms[{i}].cand_dtype {arm.get('cand_dtype')!r} "
                f"names none of {_CAND_DTYPES}"
            )
        ratio = arm.get("dist_ratio_vs_exact")
        if not (_num(ratio) and 1.0 <= ratio <= _DIST_RATIO_MAX):
            errs.append(
                f"arms[{i}] ({arm.get('cand_dtype')}:"
                f"{arm.get('pca_prune')}): dist_ratio_vs_exact "
                f"{ratio!r} outside [1.0, {_DIST_RATIO_MAX}] — the "
                "quality gate every arm must clear (below 1.0 means "
                "the probe is broken)"
            )
        p = arm.get("psnr_db")
        if not (_num(p) and p >= _PSNR_MIN_DB):
            errs.append(
                f"arms[{i}] ({arm.get('cand_dtype')}:"
                f"{arm.get('pca_prune')}): psnr_db {p!r} below the "
                f">= {_PSNR_MIN_DB} dB gate"
            )

    bm = record.get("byte_model")
    if not isinstance(bm, dict):
        errs.append("byte_model: missing object")
        bm = {}
    for key in ("sweep_fetch_int8_c4", "polish_fetch_int8",
                "coarse_row"):
        pf = bm.get(key)
        if not isinstance(pf, dict):
            errs.append(f"byte_model.{key}: missing object")
            continue
        moved, useful = pf.get("moved"), pf.get("useful")
        if not (_num(moved) and _num(useful) and 0 < useful <= moved):
            errs.append(
                f"byte_model.{key} moved={moved!r} useful={useful!r} "
                "violate 0 < useful <= moved"
            )
    if bm.get("int8_sweep_pad_bound_at_c4") is not True:
        errs.append(
            "byte_model.int8_sweep_pad_bound_at_c4 must be recorded "
            "true — the int8 sweep fetch at 4 channels is 32-sublane-"
            "tile-granule-bound (moved bytes equal f32's); omitting "
            "the negative would overstate the int8 arm"
        )

    proj = record.get("projection_modeled_not_measured")
    if not isinstance(proj, dict):
        errs.append("projection_modeled_not_measured: missing object")
        proj = {}
    base = proj.get("bytes_per_sweep_1024_r7_baseline")
    comp = proj.get("bytes_per_sweep_1024_compressed")
    if not (_num(base) and base > 0):
        errs.append(
            f"projection.bytes_per_sweep_1024_r7_baseline {base!r} "
            "not positive"
        )
    if not (_num(comp) and comp > 0):
        errs.append(
            f"projection.bytes_per_sweep_1024_compressed {comp!r} "
            "not positive"
        )
    if _num(base) and _num(comp) and comp > 0:
        ratio = base / comp
        rec_ratio = proj.get("reduction_ratio")
        if not (_num(rec_ratio) and abs(rec_ratio - ratio) < 0.01):
            errs.append(
                f"projection.reduction_ratio {rec_ratio!r} != "
                f"baseline/compressed ({ratio:.3f}) — the headline "
                "figure must be the recorded cells' quotient"
            )
        if ratio < _MIN_BYTES_RATIO:
            errs.append(
                f"projection reduction ratio {ratio:.3f} below the "
                f">= {_MIN_BYTES_RATIO}x acceptance floor (ISSUE 6)"
            )

    recipe = record.get("hardware_recipe")
    if not isinstance(recipe, dict) or not isinstance(
        recipe.get("tool"), str
    ):
        errs.append("hardware_recipe.tool: missing")
    return errs


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("record", help="path to QUANT_r11.json")
    args = ap.parse_args(argv)
    try:
        with open(args.record) as f:
            record = json.load(f)
    except (OSError, ValueError) as e:
        print(f"check_quant: cannot read {args.record}: {e}",
              file=sys.stderr)
        return 2
    errs = validate_quant(record)
    if errs:
        for e in errs:
            print(f"check_quant: {e}", file=sys.stderr)
        print(
            f"check_quant: FAIL — {len(errs)} violation(s) in "
            f"{args.record}", file=sys.stderr,
        )
        return 1
    dec = record["decision"]
    print(
        "check_quant: OK — default="
        f"{dec['default_cand_dtype']}:{dec['default_pca_prune']}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
