#!/usr/bin/env python
"""Validate a TRACE_r22.json fleet-trace-fabric artifact (round 22).

The cross-process tracing acceptance bar, held by arithmetic: the
committed record must show

  * a ROUTED request whose router-side and replica-side records were
    JOINED into one waterfall by the forwarded `X-Parent-Span`
    context, with `critical_path_coverage` >= 0.95 of the
    router-observed wall attributed to NAMED spans — re-derived here
    as attributed/total, with `unattributed_ms` the honest remainder
    (>= 0, never imputed onto neighbors);
  * a RETRY arm (a draining replica's 503 re-routed once) whose retry
    cost appears as a named `proxy_attempt` row in the same waterfall,
    and whose span-side retry count RECONCILES exactly with the
    router's `ia_route_retries_total` counter — a traced retry the
    metrics don't know about (or vice versa) means one of the two
    fabrics is lying;
  * a MIGRATION arm: `drain_replica` moved at least one pinned
    session, its wall landed in `ia_route_migration_ms`, the
    `sessions_adopt` span is present, and the session's next frame
    routed to the adoption target;
  * router tracing overhead < 2% of the request wall, measured
    min-paired-delta between a traced and an untraced router over the
    same fleet (the round-12/15/16/19 overhead discipline), published
    as the `ia_route_trace_overhead_frac` gauge the sentinel watches;
  * an honest clock model: `skew_bound_ms` is reported (>= 0) and the
    per-process phase sums never exceed that process's own total —
    walls are never mixed across clocks.

Usage:
    python tools/check_fleet_trace.py TRACE_r22.json

Runs under pytest too (tests/test_fleet_trace.py validates the
COMMITTED artifact) so tier-1 fails if the record is missing,
truncated, or any trace claim stops reproducing.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_HERE)
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

TRACE_SCHEMA_VERSION = 1
MIN_CRITICAL_PATH_COVERAGE = 0.95
MAX_TRACE_OVERHEAD_FRAC = 0.02
MIN_OVERHEAD_PAIRS = 4
_REL = 1e-6


def _num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _pos(v) -> bool:
    return _num(v) and v > 0


def _close(a, b, rel: float = _REL) -> bool:
    return abs(a - b) <= rel * max(abs(a), abs(b), 1.0)


def _validate_joined(joined, name: str, errs: List[str],
                     require_coverage: bool = True) -> None:
    """One joined fleet-trace record (serving/fleettrace.py
    `join_fleet_trace` output): schema, re-derived attribution
    arithmetic, honest skew + gap."""
    if not isinstance(joined, dict):
        errs.append(f"{name}: missing or not an object")
        return
    if joined.get("kind") != "fleet_trace":
        errs.append(f"{name}.kind: {joined.get('kind')!r} != "
                    "'fleet_trace'")
    router = joined.get("router")
    if not isinstance(router, dict):
        errs.append(f"{name}.router: missing router record")
        return
    total = router.get("total_ms")
    attributed = joined.get("attributed_ms")
    unattributed = joined.get("unattributed_ms")
    coverage = joined.get("critical_path_coverage")
    if not _pos(total):
        errs.append(f"{name}.router.total_ms: not positive "
                    f"({total!r})")
        return
    if not _num(attributed) or attributed < 0:
        errs.append(f"{name}.attributed_ms: {attributed!r}")
        return
    if attributed > total * (1.0 + _REL):
        errs.append(
            f"{name}.attributed_ms {attributed} exceeds the router-"
            f"observed total {total} (attribution must be clipped, "
            "never invented)"
        )
    if not _num(unattributed) or unattributed < 0:
        errs.append(
            f"{name}.unattributed_ms: {unattributed!r} (the gap is "
            "reported >= 0, never imputed)"
        )
    elif not _close(unattributed, max(0.0, total - attributed),
                    rel=1e-3):
        errs.append(
            f"{name}.unattributed_ms {unattributed} != total - "
            f"attributed ({total} - {attributed})"
        )
    if not _num(coverage):
        errs.append(f"{name}.critical_path_coverage: {coverage!r}")
    else:
        if not _close(coverage, attributed / total, rel=1e-3):
            errs.append(
                f"{name}.critical_path_coverage {coverage} != "
                f"attributed/total ({attributed}/{total})"
            )
        if require_coverage and coverage < MIN_CRITICAL_PATH_COVERAGE:
            errs.append(
                f"{name}.critical_path_coverage {coverage} < "
                f"{MIN_CRITICAL_PATH_COVERAGE} — the fleet waterfall "
                "leaves too much of the router-observed wall "
                "unattributed"
            )
    skew = joined.get("skew_bound_ms")
    if not _num(skew) or skew < 0:
        errs.append(f"{name}.skew_bound_ms: {skew!r} (the clock-skew "
                    "bound must be reported, >= 0)")
    rows = joined.get("rows")
    if not isinstance(rows, list) or not rows:
        errs.append(f"{name}.rows: empty waterfall")
        return
    for i, row in enumerate(rows):
        if not isinstance(row, dict) or not _num(row.get("wall_ms")) \
                or row.get("wall_ms") < 0:
            errs.append(f"{name}.rows[{i}]: malformed row {row!r}")
            return
    # Per-process honesty: each process's own phase walls must fit in
    # its own observed total (walls are never mixed across clocks).
    replica_sum = sum(
        r["wall_ms"] for r in rows if r.get("process") != "router"
    )
    if replica_sum and not any(
        isinstance(rep, dict) and rep.get("joined")
        for rep in joined.get("replicas") or []
    ):
        errs.append(f"{name}: replica rows present but no replica "
                    "record marked joined")
    procs = {r.get("process") for r in rows}
    if require_coverage and procs == {"router"}:
        errs.append(
            f"{name}.rows: router-only waterfall — no replica phases "
            "nested (the join never happened)"
        )


def validate_fleet_trace(record) -> List[str]:
    errs: List[str] = []
    if not isinstance(record, dict):
        return ["record is not a JSON object"]
    if record.get("schema_version") != TRACE_SCHEMA_VERSION:
        errs.append(
            f"schema_version {record.get('schema_version')!r} != "
            f"{TRACE_SCHEMA_VERSION}"
        )
    if record.get("kind") != "fleet_trace_load":
        errs.append(f"kind {record.get('kind')!r} != "
                    "'fleet_trace_load'")

    # -- main arm: the >= 95% attribution gate --------------------
    main_arm = record.get("main")
    if not isinstance(main_arm, dict):
        errs.append("main: missing routed-request arm")
    else:
        if main_arm.get("http_status") != 200:
            errs.append(f"main.http_status "
                        f"{main_arm.get('http_status')!r} != 200")
        _validate_joined(main_arm.get("joined"), "main.joined", errs)

    # -- retry arm: named retry span + counter reconciliation -----
    retry = record.get("retry")
    if not isinstance(retry, dict):
        errs.append("retry: missing retry arm")
    else:
        if not (_num(retry.get("retries")) and retry["retries"] >= 1):
            errs.append(f"retry.retries {retry.get('retries')!r}: the "
                        "retry arm never retried")
        if retry.get("http_status") != 200:
            errs.append(f"retry.http_status "
                        f"{retry.get('http_status')!r} != 200 (the "
                        "re-route must have succeeded)")
        joined = retry.get("joined")
        _validate_joined(joined, "retry.joined", errs,
                         require_coverage=False)
        if isinstance(joined, dict):
            rows = joined.get("rows") or []
            if not any(
                isinstance(r, dict)
                and str(r.get("phase", "")).startswith("proxy_attempt")
                and "draining" in str(r.get("phase"))
                for r in rows
            ):
                errs.append(
                    "retry.joined.rows: no proxy_attempt[draining...] "
                    "row — the retry cost is not a named span"
                )
            if not _close(float(joined.get("retry_ms") or 0.0),
                          float(retry.get("retry_ms") or -1.0),
                          rel=1e-3):
                errs.append(
                    f"retry.retry_ms {retry.get('retry_ms')!r} != "
                    f"joined.retry_ms {joined.get('retry_ms')!r}"
                )

    rec = record.get("reconciliation")
    if not isinstance(rec, dict):
        errs.append("reconciliation: missing")
    else:
        counter = rec.get("counter_retries_total")
        spans = rec.get("span_retry_attempts")
        if not _num(counter) or not _num(spans):
            errs.append(
                f"reconciliation: non-numeric cells ({counter!r}, "
                f"{spans!r})"
            )
        elif counter != spans:
            errs.append(
                f"reconciliation: ia_route_retries_total {counter} != "
                f"{spans} retry-reason proxy_attempt entries in the "
                "access log — the span fabric and the metrics fabric "
                "disagree"
            )

    # -- migration arm --------------------------------------------
    mig = record.get("migration")
    if not isinstance(mig, dict):
        errs.append("migration: missing drain-migration arm")
    else:
        if not _pos(mig.get("migration_ms")):
            errs.append(f"migration.migration_ms "
                        f"{mig.get('migration_ms')!r}: not positive")
        if not (_num(mig.get("sessions")) and mig["sessions"] >= 1):
            errs.append(f"migration.sessions {mig.get('sessions')!r}: "
                        "no session migrated")
        spans = mig.get("spans")
        if not isinstance(spans, list) or \
                "sessions_adopt" not in spans:
            errs.append(
                f"migration.spans {spans!r}: no sessions_adopt span — "
                "the adopt hop is invisible in the trace fabric"
            )
        if mig.get("post_migration_routed_to") != mig.get("target"):
            errs.append(
                "migration: the migrated session's next frame routed "
                f"to {mig.get('post_migration_routed_to')!r}, not the "
                f"adoption target {mig.get('target')!r}"
            )

    # -- overhead -------------------------------------------------
    ovh = record.get("overhead")
    if not isinstance(ovh, dict):
        errs.append("overhead: missing")
    else:
        frac = ovh.get("frac")
        if not _num(frac) or frac < 0:
            errs.append(f"overhead.frac: {frac!r}")
        elif frac >= MAX_TRACE_OVERHEAD_FRAC:
            errs.append(
                f"overhead.frac {frac} >= {MAX_TRACE_OVERHEAD_FRAC} — "
                "router tracing is not within the telemetry budget"
            )
        pairs = ovh.get("pairs")
        if not _num(pairs) or pairs < MIN_OVERHEAD_PAIRS:
            errs.append(f"overhead.pairs {pairs!r} < "
                        f"{MIN_OVERHEAD_PAIRS}")
    return errs


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("record", help="path to TRACE_r22.json")
    args = ap.parse_args(argv)
    try:
        with open(args.record) as f:
            record = json.load(f)
    except (OSError, ValueError) as e:
        print(f"check_fleet_trace: cannot read {args.record}: {e}")
        return 2
    errs = validate_fleet_trace(record)
    if errs:
        print(f"check_fleet_trace: {args.record} INVALID:")
        for e in errs:
            print(f"  - {e}")
        return 1
    main_j = record["main"]["joined"]
    print(
        f"check_fleet_trace: {args.record} OK (coverage "
        f"{main_j['critical_path_coverage']}, skew bound "
        f"{main_j['skew_bound_ms']} ms, retries "
        f"{record['retry']['retries']}, migration "
        f"{record['migration']['migration_ms']} ms, overhead "
        f"{record['overhead']['frac']})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
