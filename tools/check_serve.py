#!/usr/bin/env python
"""Validate a SERVE_r13.json serving-tier artifact (round 13).

The serving acceptance bar, enforced by a validator instead of trusted
to prose: the executable cache must have MEASURABLY saved the second
same-shape request its prologue compile (latency_delta_ms > 0, warm
under cold), the steady-state sweep point must actually run warm
(hit_ratio >= 0.5 with nothing shed), the overload point must have
produced real backpressure (at least one 429), every sweep point's
arithmetic must close (completed + shed + failed == requests, p50 <=
p99), the final admission ledger must balance (requests == admitted +
shed, admitted == completed + failed — nothing lost, nothing double-
counted), and the sentinel's serving check must have graded the run
"ok" — a ledger the daemon's own invariant check rejects is not an
artifact, it is a bug report.

Usage:
    python tools/check_serve.py SERVE_r13.json

Runs under pytest too (tests/test_serving.py validates the COMMITTED
artifact) so tier-1 fails if the record is missing, truncated, or
structurally degraded.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List

SERVE_SCHEMA_VERSION = 1


def _num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def validate_serve(record: dict) -> List[str]:
    """Return a list of violations (empty = valid)."""
    errs: List[str] = []
    if not isinstance(record, dict):
        return ["record is not a JSON object"]
    if record.get("schema_version") != SERVE_SCHEMA_VERSION:
        errs.append(
            f"schema_version {record.get('schema_version')!r} != "
            f"{SERVE_SCHEMA_VERSION}"
        )
    if record.get("kind") != "serve":
        errs.append(f"kind {record.get('kind')!r} != 'serve'")
    size = record.get("proxy_size")
    if not (_num(size) and size >= 16):
        errs.append(f"proxy_size {size!r} is not a size >= 16")

    cache = record.get("cache")
    if not isinstance(cache, dict):
        errs.append("cache: missing object")
        cache = {}
    cold, warm = cache.get("cold_ms"), cache.get("warm_ms")
    delta = cache.get("latency_delta_ms")
    if not (_num(cold) and cold > 0):
        errs.append(f"cache.cold_ms {cold!r} is not a positive number")
    if not (_num(warm) and warm > 0):
        errs.append(f"cache.warm_ms {warm!r} is not a positive number")
    if not (_num(delta) and delta > 0):
        errs.append(
            f"cache.latency_delta_ms {delta!r} is not > 0 — the "
            "second same-shape request must demonstrably skip the "
            "prologue compile"
        )
    if _num(cold) and _num(warm) and cold <= warm:
        errs.append(
            f"cache.cold_ms {cold} <= warm_ms {warm} — a 'hit' that "
            "is no faster than the compile is not a hit"
        )
    for k in ("hits", "misses"):
        v = cache.get(k)
        if not (_num(v) and v >= 1):
            errs.append(f"cache.{k} {v!r} is not a count >= 1")

    sweep = record.get("sweep")
    if not isinstance(sweep, list) or not sweep:
        errs.append("sweep: missing/empty list")
        sweep = []
    any_shed = False
    any_warm_steady = False
    for i, pt in enumerate(sweep):
        if not isinstance(pt, dict):
            errs.append(f"sweep[{i}]: not an object")
            continue
        name = f"sweep[{i}] (clients={pt.get('clients')!r})"
        for k in ("clients", "requests", "completed", "shed", "failed"):
            if not (_num(pt.get(k)) and pt.get(k) >= 0):
                errs.append(f"{name}: {k} {pt.get(k)!r} is not a "
                            "non-negative number")
        if all(_num(pt.get(k)) for k in ("requests", "completed",
                                         "shed", "failed")):
            if pt["completed"] + pt["shed"] + pt["failed"] != \
                    pt["requests"]:
                errs.append(
                    f"{name}: completed {pt['completed']} + shed "
                    f"{pt['shed']} + failed {pt['failed']} != requests "
                    f"{pt['requests']}"
                )
            if pt["shed"] >= 1:
                any_shed = True
        hr = pt.get("hit_ratio")
        if not (_num(hr) and 0.0 <= hr <= 1.0):
            errs.append(f"{name}: hit_ratio {hr!r} not in [0, 1]")
        p50, p99 = pt.get("p50_ms"), pt.get("p99_ms")
        if _num(pt.get("completed")) and pt["completed"] >= 1:
            if not (_num(p50) and _num(p99)):
                errs.append(
                    f"{name}: completed requests but p50_ms/p99_ms "
                    f"are {p50!r}/{p99!r}"
                )
            elif p50 > p99:
                errs.append(f"{name}: p50_ms {p50} > p99_ms {p99}")
        if (
            _num(pt.get("shed")) and pt["shed"] == 0
            and _num(hr) and hr >= 0.5
        ):
            any_warm_steady = True
    if sweep and not any_shed:
        errs.append(
            "no sweep point shed a request — the overload arm never "
            "produced backpressure (429s are an acceptance criterion, "
            "not an error mode)"
        )
    if sweep and not any_warm_steady:
        errs.append(
            "no steady-state sweep point (shed == 0) ran warm "
            "(hit_ratio >= 0.5) — the executable cache is not doing "
            "its job under sustained same-shape load"
        )

    ledger = record.get("ledger")
    if not isinstance(ledger, dict):
        errs.append("ledger: missing object")
        ledger = {}
    if all(_num(ledger.get(k)) for k in ("requests", "admitted",
                                         "shed")):
        if ledger["requests"] != ledger["admitted"] + ledger["shed"]:
            errs.append(
                f"ledger: requests {ledger['requests']} != admitted "
                f"{ledger['admitted']} + shed {ledger['shed']}"
            )
    else:
        errs.append("ledger: requests/admitted/shed must be numbers")
    if all(_num(ledger.get(k)) for k in ("admitted", "completed",
                                         "failed")):
        if ledger["admitted"] != ledger["completed"] + ledger["failed"]:
            errs.append(
                f"ledger: admitted {ledger['admitted']} != completed "
                f"{ledger['completed']} + failed {ledger['failed']} — "
                "an unbalanced final ledger means a request was lost "
                "or double-counted"
            )
    else:
        errs.append("ledger: admitted/completed/failed must be numbers")

    if record.get("serving_check") != "ok":
        errs.append(
            f"serving_check {record.get('serving_check')!r} != 'ok' — "
            "the sentinel's own ledger invariants must grade the run "
            "clean"
        )
    return errs


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="SERVE_r13.json to validate")
    args = ap.parse_args(argv)
    try:
        with open(args.path) as f:
            record = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"check_serve: cannot read {args.path}: {e}")
        return 1
    errs = validate_serve(record)
    if errs:
        print(f"check_serve: {args.path} INVALID:")
        for e in errs:
            print(f"  - {e}")
        return 1
    cache = record.get("cache", {})
    print(
        f"check_serve: {args.path} OK "
        f"(compile saved {cache.get('latency_delta_ms')} ms on repeat "
        f"shape; {len(record.get('sweep', []))} sweep points; ledger "
        f"{record.get('ledger')})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
