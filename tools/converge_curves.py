"""Search-budget convergence curves for configs 2/5's content families
(VERDICT r5 task 2 / missing 1): is the ~32 dB ceiling on the
artistic-filter and NPR families SEARCH-bound (more pm/em budget keeps
buying dB) or CONTENT-bound (the curve is flat at the current
schedule)?

Sweeps pm_iters x em_iters on both families against their exact
brute oracles (one oracle per em_iters — the EM loop feeds each
iteration's estimate back into the features, so the exact pipeline
differs per em) and prints one JSON line of PSNR-vs-budget curves —
the tools/kappa_curves.py pattern with the budget axis instead of the
kappa axis.

No accelerator was reachable in round 8, so the default size is the
CPU-feasible 128 (pure-XLA matcher path — the same sweep structure,
candidates, and kappa rule as the kernel path's polish; the kernel
changes the bulk-search engine, not the acceptance family).  The
curve's SHAPE is the measurement: a flat curve at small scale is
necessary-but-not-sufficient evidence for "content-bound", recorded
with that caveat (CONVERGE_r08.json); re-run at 512/1024 on hardware
to confirm.

    python tools/converge_curves.py [size] [family|all]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax.numpy as jnp

from image_analogies_tpu.utils.cache import enable_compilation_cache

enable_compilation_cache()

from image_analogies_tpu import SynthConfig, create_image_analogy, psnr
from image_analogies_tpu.utils.examples import artistic_filter, npr_frames

# The two content families whose acceptance rows sit ~3 dB below the
# super-res configs (BENCH_r05: config 2 31.66 dB, config 5 32.37 dB),
# with their configs' own kappa.
_FAMILIES = {
    "artistic_config2": {"loader": "artistic", "kappa": 5.0},
    "npr_config5": {"loader": "npr", "kappa": 2.0},
}

# Grid sized for the CPU-feasible default (a full 4x3 grid x 2
# families overran a 50-min box budget; the knee question only needs
# below/at/above the shipping pm=6 and the em sweep).
_PM_GRID = (2, 6, 10)
_EM_GRID = (1, 2, 3)


def _content(loader: str, size: int):
    if loader == "npr":
        a, ap, frames = npr_frames(n_frames=1, size=size)
        return a, ap, np.asarray(frames)[0]
    return artistic_filter(size)


def run_family(name: str, spec: dict, size: int) -> dict:
    a_h, ap_h, b_h = _content(spec["loader"], size)
    a = jnp.asarray(a_h, jnp.float32)
    ap = jnp.asarray(ap_h, jnp.float32)
    b = jnp.asarray(b_h, jnp.float32)
    kappa = spec["kappa"]

    oracles = {}
    for em in _EM_GRID:
        oracles[em] = np.asarray(
            create_image_analogy(
                a, ap, b,
                SynthConfig(
                    levels=5, matcher="brute", em_iters=em, kappa=kappa
                ),
            )
        )
    rows = []
    for em in _EM_GRID:
        for pm in _PM_GRID:
            t0 = time.perf_counter()
            out = np.asarray(
                create_image_analogy(
                    a, ap, b,
                    SynthConfig(
                        levels=5, matcher="patchmatch", em_iters=em,
                        pm_iters=pm, kappa=kappa,
                    ),
                )
            )
            rows.append({
                "em_iters": em,
                "pm_iters": pm,
                "psnr_vs_oracle_db": round(psnr(out, oracles[em]), 2),
                "wall_s": round(time.perf_counter() - t0, 3),
            })
            print(f"# {name} {rows[-1]}", file=sys.stderr, flush=True)
    # Knee analysis against the shipping schedule (em=2, pm=6 — the
    # acceptance-table schedule for configs 2/5).
    by = {(r["em_iters"], r["pm_iters"]): r["psnr_vs_oracle_db"]
          for r in rows}
    current = by.get((2, 6))
    best = max(rows, key=lambda r: r["psnr_vs_oracle_db"])
    return {
        "family": name,
        "kappa": kappa,
        "curves": rows,
        "current_schedule_db": current,
        "best": best,
        "headroom_db": (
            round(best["psnr_vs_oracle_db"] - current, 2)
            if current is not None else None
        ),
    }


def main():
    size = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    which = sys.argv[2] if len(sys.argv) > 2 else "all"
    out = {"size": size, "pm_grid": list(_PM_GRID),
           "em_grid": list(_EM_GRID), "families": []}
    for name, spec in _FAMILIES.items():
        if which not in ("all", name, spec["loader"]):
            continue
        out["families"].append(run_family(name, spec, size))
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
