"""Probe: at what (n_a, n_b) does the exact-NN kernel execution wedge?

Round-5 wedge hunt.  The 3072^2 lean-brute oracle's first level-0
search chunk wedges (client asleep, 0 CPU) while 8 GB allocations and
multi-GB assembly executions complete fine — so the damage is specific
to the exact-NN kernel execution shape.  Run ONE shape per process
(isolation: a wedged session must not poison the next probe):

    python tools/probe_nn_wedge.py N_A N_B [tq] [ta]
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax.numpy as jnp

from image_analogies_tpu.utils.cache import enable_compilation_cache

enable_compilation_cache()

from image_analogies_tpu.kernels.nn_brute import exact_nn_pallas


def main():
    n_a = int(float(sys.argv[1]))
    n_b = int(float(sys.argv[2]))
    tq = int(sys.argv[3]) if len(sys.argv) > 3 else 2048
    ta = int(sys.argv[4]) if len(sys.argv) > 4 else 256
    d = 128
    rng = np.random.default_rng(0)
    t0 = time.time()
    f_a = jnp.asarray(rng.random((n_a, d), np.float32), jnp.bfloat16)
    f_b = jnp.asarray(rng.random((n_b, d), np.float32), jnp.bfloat16)
    float(f_a[0, 0]); float(f_b[0, 0])
    print(f"tables up at {round(time.time()-t0,1)}s", flush=True)
    t0 = time.time()
    idx, dist = exact_nn_pallas(
        f_b, f_a, match_dtype=jnp.bfloat16, interpret=False, tq=tq, ta=ta
    )
    s = float(dist.sum())
    print(
        f"OK n_a={n_a} n_b={n_b} tq={tq} ta={ta} "
        f"wall={round(time.time()-t0,1)}s sum={s}", flush=True,
    )


if __name__ == "__main__":
    main()
