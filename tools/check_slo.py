#!/usr/bin/env python
"""Validate an SLO_r15.json serving-SLO artifact (round 15).

The observability acceptance bar, enforced by a validator instead of
trusted to prose: the committed record must carry a real SLO report
graded from the request-duration histogram (objectives with burn
rates, none violated), a measured warm p99 and availability that MEET
the declared objectives, a sample of the per-request ids the daemon
echoed (request-scoped tracing is the tentpole — the artifact proves
ids flowed end to end), and one reconstructed critical path whose
phase attribution sums to within 5% of the measured end-to-end
latency (the `ia-synth trace` acceptance bound, frozen into the
artifact).

Usage:
    python tools/check_slo.py SLO_r15.json

Runs under pytest too (tests/test_serving.py validates the COMMITTED
artifact) so tier-1 fails if the record is missing, truncated, or
structurally degraded.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List

SLO_SCHEMA_VERSION = 1

# ia-synth trace acceptance bound: phase attribution must explain the
# measured end-to-end latency to within this fraction.
CRITICAL_PATH_GAP_FRAC = 0.05

_OBJECTIVE_KINDS = ("latency", "availability", "shed_rate")
_OBJECTIVE_STATUSES = ("ok", "fast_burn", "exhausted", "no_data")
_PHASES = ("queue_ms", "compile_ms", "execute_ms", "demux_ms")


def _num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def validate_slo(record: dict) -> List[str]:
    """Return a list of violations (empty = valid)."""
    errs: List[str] = []
    if not isinstance(record, dict):
        return ["record is not a JSON object"]
    if record.get("schema_version") != SLO_SCHEMA_VERSION:
        errs.append(
            f"schema_version {record.get('schema_version')!r} != "
            f"{SLO_SCHEMA_VERSION}"
        )
    if record.get("kind") != "slo":
        errs.append(f"kind {record.get('kind')!r} != 'slo'")
    rnd = record.get("round")
    if not (_num(rnd) and rnd >= 15):
        errs.append(f"round {rnd!r} is not a round >= 15")

    # -- the embedded SLO report (evaluate_slo output).
    slo = record.get("slo")
    if not isinstance(slo, dict):
        errs.append("slo: missing report object")
        slo = {}
    objectives = slo.get("objectives")
    if not isinstance(objectives, list) or not objectives:
        errs.append("slo.objectives: missing/empty list")
        objectives = []
    targets = {}
    for i, obj in enumerate(objectives):
        if not isinstance(obj, dict):
            errs.append(f"slo.objectives[{i}]: not an object")
            continue
        name = obj.get("name") or f"objectives[{i}]"
        if obj.get("kind") not in _OBJECTIVE_KINDS:
            errs.append(
                f"{name}: kind {obj.get('kind')!r} not in "
                f"{_OBJECTIVE_KINDS}"
            )
        target = obj.get("target")
        if not (_num(target) and 0.0 < target <= 1.0):
            errs.append(f"{name}: target {target!r} not in (0, 1]")
        else:
            targets[obj.get("kind")] = target
        status = obj.get("status")
        if status not in _OBJECTIVE_STATUSES:
            errs.append(
                f"{name}: status {status!r} not in {_OBJECTIVE_STATUSES}"
            )
        if status == "exhausted":
            errs.append(
                f"{name}: error budget exhausted — a committed "
                "artifact must not document an SLO breach"
            )
        burn = obj.get("burn_rate")
        budget = obj.get("budget_remaining")
        if status == "no_data":
            continue
        if not (_num(burn) and burn >= 0.0):
            errs.append(f"{name}: burn_rate {burn!r} is not a "
                        "non-negative number")
        if not _num(budget):
            errs.append(f"{name}: budget_remaining {budget!r} is not "
                        "a number")
        elif _num(burn) and abs((burn + budget) - 1.0) > 1e-3:
            errs.append(
                f"{name}: burn_rate {burn} + budget_remaining "
                f"{budget} != 1"
            )
    verdict = slo.get("verdict")
    if verdict not in ("ok", "degraded", "skipped"):
        errs.append(
            f"slo.verdict {verdict!r} is not ok/degraded (a committed "
            "artifact must not be violated)"
        )

    # -- headline numbers must meet the declared objectives.
    p99 = record.get("p99_warm_ms")
    if not (_num(p99) and p99 > 0):
        errs.append(f"p99_warm_ms {p99!r} is not a positive number")
    avail = record.get("availability")
    if not (_num(avail) and 0.0 <= avail <= 1.0):
        errs.append(f"availability {avail!r} not in [0, 1]")
    elif "availability" in targets and avail < targets["availability"]:
        errs.append(
            f"availability {avail} < objective target "
            f"{targets['availability']}"
        )

    # -- request-scoped tracing proof: echoed ids + one critical path.
    rids = record.get("request_ids")
    if not (isinstance(rids, list) and rids
            and all(isinstance(r, str) and r for r in rids)):
        errs.append(
            "request_ids: must be a non-empty list of non-empty "
            "strings (the ids the daemon echoed back)"
        )
    elif len(set(rids)) != len(rids):
        errs.append("request_ids: duplicate ids in sample")

    cp = record.get("critical_path")
    if not isinstance(cp, dict):
        errs.append("critical_path: missing object")
        cp = {}
    if not (isinstance(cp.get("request_id"), str) and cp.get("request_id")):
        errs.append(
            f"critical_path.request_id {cp.get('request_id')!r} is "
            "not a non-empty string"
        )
    total = cp.get("total_ms")
    if not (_num(total) and total > 0):
        errs.append(
            f"critical_path.total_ms {total!r} is not a positive number"
        )
    phases = cp.get("phases")
    if not isinstance(phases, dict):
        errs.append("critical_path.phases: missing object")
        phases = {}
    attributed = 0.0
    for k in _PHASES:
        v = phases.get(k)
        if not (_num(v) and v >= 0.0):
            errs.append(
                f"critical_path.phases.{k} {v!r} is not a "
                "non-negative number"
            )
        else:
            attributed += v
    if _num(total) and total > 0 and not errs_in_phases(phases):
        gap_frac = abs(total - attributed) / total
        if gap_frac > CRITICAL_PATH_GAP_FRAC:
            errs.append(
                f"critical_path: phases sum {attributed:.3f} ms "
                f"deviates {100 * gap_frac:.1f}% from total_ms "
                f"{total:.3f} (bound {100 * CRITICAL_PATH_GAP_FRAC:.0f}%)"
            )
    return errs


def errs_in_phases(phases: dict) -> bool:
    return any(
        not (_num(phases.get(k)) and phases.get(k) >= 0.0)
        for k in _PHASES
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="SLO_r15.json to validate")
    args = ap.parse_args(argv)
    try:
        with open(args.path) as f:
            record = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"check_slo: cannot read {args.path}: {e}")
        return 1
    errs = validate_slo(record)
    if errs:
        print(f"check_slo: {args.path} INVALID:")
        for e in errs:
            print(f"  - {e}")
        return 1
    cp = record.get("critical_path", {})
    print(
        f"check_slo: {args.path} OK (verdict "
        f"{record.get('slo', {}).get('verdict')!r}; p99 warm "
        f"{record.get('p99_warm_ms')} ms; availability "
        f"{record.get('availability')}; critical path "
        f"{cp.get('request_id')!r} total {cp.get('total_ms')} ms)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
