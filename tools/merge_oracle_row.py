"""Merge the 4096^2 full-oracle result (tools/full_oracle.py) into the
SCALE_r{N}.json artifact and refresh its comment.

Usage: python tools/merge_oracle_row.py <full_oracle_json_line> <scale.json>
where <full_oracle_json_line> is a file holding the one-line JSON that
`python tools/full_oracle.py 4096` printed.
"""

import json
import sys

COMMENT = (
    "Large-image scaling rows, tools/scale_bench.py, TPU v5e-1, "
    "2026-07-31, round-4 HBM-streaming kernel (no banding at any size, "
    "full channel set everywhere).  Quality: EVERY row carries PSNR vs "
    "a FULL-SYNTHESIS exact-NN oracle (brute synthesis at every "
    "level/EM step), plus a stratified-jittered exact probe (1M pixels "
    "or half the image, bootstrap 95% CI on the achieved/exact "
    "mean-distance ratio, exact-match fraction) in the lean bf16 "
    "metric at the EM fixed point.  <=2048^2 oracles run the standard "
    "f32-table brute path (crash-safety: kernels/nn_brute.py "
    "_MAX_TILE_ELEMS + models/analogy.py _SAFE_EXEC_DIST_ELEMS).  The "
    "4096^2 oracle (tools/full_oracle.py) runs the round-4 LEAN-BRUTE "
    "path (models/analogy.lean_brute_em_step, cfg.brute_lean_bytes): "
    "exact search over the same chunk-assembled bf16 tables the "
    "production path matches in — the f32-table oracle cannot exist "
    "at 4096^2 (two lane-padded tables = 17.2 GB vs 16 GB HBM).  "
    "Cross-validation at 1024^2 (both oracles on one run): PSNR vs "
    "f32 oracle 35.69 dB, vs bf16-table oracle 37.81 dB, oracles "
    "agreeing at 36.71 dB — the bf16-table oracle is the "
    "matched-metric one at lean sizes and its PSNR reads ~2 dB "
    "higher; the 4096^2 row reports it with the oracle named in the "
    "row.  Probe calibration anchors: 1.496 ~ 35.69 dB, "
    "1.597 ~ 35.24 dB (f32-oracle rows)."
)


def main():
    line_file, scale_file = sys.argv[1], sys.argv[2]
    result = None
    for line in open(line_file):
        line = line.strip()
        if line.startswith("{"):
            result = json.loads(line)
    assert result and "psnr_vs_full_oracle_db" in result, result
    art = json.load(open(scale_file))
    for row in art["rows"]:
        if row["size"] == result["size"]:
            row["psnr_vs_full_oracle_db"] = result["psnr_vs_full_oracle_db"]
            row["oracle_wall_s"] = result["oracle_wall_s"]
            row["oracle_kind"] = result["oracle"]
            break
    else:
        raise SystemExit(f"no row for size {result['size']}")
    art["comment"] = COMMENT
    with open(scale_file, "w") as f:
        json.dump(art, f, indent=1)
        f.write("\n")
    print(f"merged {result['size']} oracle row into {scale_file}")


if __name__ == "__main__":
    main()
