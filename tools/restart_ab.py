"""A/B: uniform vs coarse/field-informed global restarts
(kernels/patchmatch_tile._RESTART_MODE; VERDICT r5 task 3).

The 4096^2 exact-distance ratio drifts monotonically with size
(SCALE dist_ratio_vs_exact 1.496 -> 1.668) while the kernel's K_GLOBAL
restart slots stay uniform-over-A.  The "coarse" mode seeds them from
the evolving field (= the parent level's converged field at EM entry)
at random other positions.

KILL CRITERION, pre-stated (the polish_ab.py discipline): "coarse"
becomes the default iff, on hardware at 4096^2 defaults, the final
dist_ratio_vs_exact drops to <= 1.58 at <= 1.05x wall and every
published PSNR family stays within +-0.1 dB.  This round (no
accelerator) records the interpret-mode proxy at a small size: the
proxy must show a non-negative mean-distance improvement to justify
spending the hardware session; a flat/negative proxy kills the probe
without burning chip time.  Either way the result lands in
POLISH_r08.json's satellites section.

    python tools/restart_ab.py [size] [levels]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax.numpy as jnp

from image_analogies_tpu.utils.cache import enable_compilation_cache

enable_compilation_cache()

from image_analogies_tpu import SynthConfig, create_image_analogy, psnr
from image_analogies_tpu.utils.examples import super_resolution


def _clear_caches():
    import image_analogies_tpu.models.analogy as an

    an._level_fn.cache_clear()
    an._em_step_fn.cache_clear()


def measure(mode: str, a, ap, b, cfg, exact_dist0: float, oracle):
    import image_analogies_tpu.kernels.patchmatch_tile as pt

    pt._RESTART_MODE = mode
    _clear_caches()
    # Warm-up run first (compile): the mode flip cleared the level-fn
    # caches, so the first call pays trace+compile — timing it would
    # decide the <= 1.05x wall criterion on compile variance, not on
    # the sweeps (tools/polish_stream_ab.py's protocol).
    create_image_analogy(a, ap, b, cfg)
    t0 = time.perf_counter()
    aux = create_image_analogy(a, ap, b, cfg, return_aux=True)
    bp = np.asarray(aux["bp"])
    wall = round(time.perf_counter() - t0, 3)
    d0 = aux["dist"][0]
    mean_d = float(np.asarray(d0).mean())
    return {
        "mode": mode,
        "wall_s": wall,
        "level0_mean_dist": round(mean_d, 6),
        "dist_ratio_vs_exact": (
            round(mean_d / exact_dist0, 4) if exact_dist0 else None
        ),
        "psnr_vs_oracle_db": round(psnr(bp, oracle), 2),
    }


def main():
    size = int(sys.argv[1]) if len(sys.argv) > 1 else 256
    levels = int(sys.argv[2]) if len(sys.argv) > 2 else 3
    a, ap, b = super_resolution(size)
    a = jnp.asarray(a, jnp.float32)
    ap = jnp.asarray(ap, jnp.float32)
    b = jnp.asarray(b, jnp.float32)

    import jax

    on_tpu = any(d.platform != "cpu" for d in jax.devices())
    cfg = SynthConfig(
        levels=levels, matcher="patchmatch", em_iters=2, pm_iters=6,
        pallas_mode="auto" if on_tpu else "interpret",
    )
    oracle_aux = create_image_analogy(
        a, ap, b,
        SynthConfig(levels=levels, matcher="brute", em_iters=2),
        return_aux=True,
    )
    oracle = np.asarray(oracle_aux["bp"])
    exact_dist0 = float(np.asarray(oracle_aux["dist"][0]).mean())

    res = {
        "size": size,
        "levels": levels,
        "backend": "tpu" if on_tpu else "cpu-interpret-proxy",
        "exact_level0_mean_dist": round(exact_dist0, 6),
        "uniform": measure("uniform", a, ap, b, cfg, exact_dist0, oracle),
        "coarse": measure("coarse", a, ap, b, cfg, exact_dist0, oracle),
        "kill_criterion": (
            "coarse ships iff hardware 4096^2 dist_ratio_vs_exact <= "
            "1.58 at <= 1.05x wall and published PSNR families within "
            "+-0.1 dB; the CPU proxy must improve mean dist to justify "
            "the hardware run"
        ),
    }
    u, c = res["uniform"], res["coarse"]
    res["delta"] = {
        "dist_ratio": (
            round(c["dist_ratio_vs_exact"] - u["dist_ratio_vs_exact"], 4)
            if u["dist_ratio_vs_exact"] and c["dist_ratio_vs_exact"]
            else None
        ),
        "psnr_db": round(
            c["psnr_vs_oracle_db"] - u["psnr_vs_oracle_db"], 2
        ),
        "wall_x": round(c["wall_s"] / u["wall_s"], 3),
    }
    # Leave the module default untouched for any embedding process.
    import image_analogies_tpu.kernels.patchmatch_tile as pt

    pt._RESTART_MODE = os.environ.get("IA_RESTART_MODE", "uniform")
    print(json.dumps(res), flush=True)


if __name__ == "__main__":
    main()
