"""Microbench: exact-metric candidate evaluation variants (polish hot op)."""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax
import jax.numpy as jnp

from image_analogies_tpu.utils.cache import enable_compilation_cache

enable_compilation_cache()


def _sync(x):
    return float(jnp.sum(x))


def timeit(fn, *args, reps=8):
    out = fn(*args)
    _sync(jax.tree_util.tree_leaves(out)[0])
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    _sync(jax.tree_util.tree_leaves(out)[0])
    return (time.perf_counter() - t0) / reps * 1000


def main():
    n, d = 1024 * 1024, 68
    rng = np.random.default_rng(0)
    f_a = jnp.asarray(rng.random((n, d), np.float32))
    f_b = jnp.asarray(rng.random((n, d), np.float32))
    f_a16 = f_a.astype(jnp.bfloat16)
    f_b16 = f_b.astype(jnp.bfloat16)
    idx = jnp.asarray(rng.integers(0, n, n, dtype=np.int32))
    idx12 = jnp.asarray(rng.integers(0, n, (12, n), dtype=np.int32))

    res = {}

    @jax.jit
    def single_f32(fb, fa, ix):
        rows = jnp.take(fa, ix, axis=0)
        return jnp.sum((fb - rows) ** 2, axis=-1)

    res["single_f32_ms"] = timeit(single_f32, f_b, f_a, idx)

    @jax.jit
    def single_bf16(fb, fa, ix):
        rows = jnp.take(fa, ix, axis=0).astype(jnp.float32)
        return jnp.sum((fb.astype(jnp.float32) - rows) ** 2, axis=-1)

    res["single_bf16_ms"] = timeit(single_bf16, f_b16, f_a16, idx)

    @jax.jit
    def batched12_f32(fb, fa, ix):
        rows = jnp.take(fa, ix.reshape(-1), axis=0).reshape(12, n, d)
        return jnp.sum((fb[None] - rows) ** 2, axis=-1)

    res["batched12_f32_ms"] = timeit(batched12_f32, f_b, f_a, idx12)

    @jax.jit
    def batched12_bf16(fb, fa, ix):
        rows = jnp.take(fa, ix.reshape(-1), axis=0).astype(jnp.float32)
        rows = rows.reshape(12, n, d)
        return jnp.sum((fb.astype(jnp.float32)[None] - rows) ** 2, axis=-1)

    res["batched12_bf16_ms"] = timeit(batched12_bf16, f_b16, f_a16, idx12)

    # Pure gather (no math): what does the row fetch alone cost?
    @jax.jit
    def gather_only(fa, ix):
        return jnp.take(fa, ix, axis=0)

    res["gather_only_f32_ms"] = timeit(gather_only, f_a, idx)
    res["gather_only_bf16_ms"] = timeit(gather_only, f_a16, idx)

    # Sequential-read ceiling for comparison.
    @jax.jit
    def seq_read(fa, fb):
        return jnp.sum((fa - fb) ** 2, axis=-1)

    res["seq_diff_f32_ms"] = timeit(seq_read, f_a, f_b)

    # --- Is the ~16-19 GB/s gather rate a locality effect or a per-row
    # floor?  Three index distributions bound it: uniform-random (the
    # baseline above), SORTED (maximum spatial locality a re-ordering
    # could ever buy), and IOTA (perfectly sequential — the degenerate
    # gather that a streaming copy could replace).  If sorted ~= random,
    # no sort/cluster pipeline can beat the floor; if iota is also at
    # the floor, the cost is per-row issue overhead in XLA's gather
    # lowering, not HBM physics.
    idx_sorted = jnp.sort(idx)
    res["gather_sorted_bf16_ms"] = timeit(gather_only, f_a16, idx_sorted)
    idx_iota = jnp.arange(n, dtype=jnp.int32)
    res["gather_iota_bf16_ms"] = timeit(gather_only, f_a16, idx_iota)

    # Coherent-field gather: indices from a piecewise-smooth NN field
    # (the polish's real distribution after convergence) — neighboring
    # queries fetch neighboring rows.
    blk = rng.integers(0, n, n // 256, dtype=np.int32)
    idx_coh = jnp.asarray(
        (np.repeat(blk, 256) + np.tile(np.arange(256), n // 256))
        .clip(0, n - 1)
        .astype(np.int32)
    )
    res["gather_coherent_bf16_ms"] = timeit(gather_only, f_a16, idx_coh)

    # Sort -> gather -> unsort pipeline: total cost if the polish
    # re-ordered its candidate evaluations for locality.
    @jax.jit
    def gather_via_sort(fa, ix):
        order = jnp.argsort(ix)
        rows = jnp.take(fa, ix[order], axis=0)
        inv = jnp.zeros_like(order).at[order].set(
            jnp.arange(ix.shape[0], dtype=order.dtype)
        )
        return jnp.take(rows, inv, axis=0)

    res["gather_via_sort_bf16_ms"] = timeit(gather_via_sort, f_a16, idx)

    for k, v in res.items():
        res[k] = round(v, 3)
    res["note"] = "n=1M rows, D=68 (pads to 128 lanes)"
    print(json.dumps(res, indent=1))


if __name__ == "__main__":
    main()
