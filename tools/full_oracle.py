"""Full-synthesis exact-NN oracle PSNR at sizes past the f32-table wall.

Round 4 measured full-oracle PSNR up to 2048^2 (SCALE_r04) and bounded
4096^2 by a calibrated probe: the standard brute path's two lane-padded
f32 tables are 17.2 GB at 4096^2 against 16 GB of HBM.  The lean-brute
path (models/analogy.lean_brute_em_step) removes that wall — exact
search over chunk-assembled bf16 tables, eager chunked executions — so
the 4096^2 row can carry a measured full-oracle PSNR like the smaller
rows.

Modes:
  python tools/full_oracle.py validate   # 1024^2: lean-brute oracle vs
                                         # the recorded f32 oracle —
                                         # quantifies the bf16-table
                                         # metric swap (~minutes)
  python tools/full_oracle.py 4096       # the real run (~4 h): pm
                                         # synthesis + lean-brute full
                                         # oracle + PSNR; one JSON line

State is checkpointed to tools/_oracle_out/ (pm output, oracle output)
so a tunnel hiccup doesn't forfeit completed phases.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax.numpy as jnp

from image_analogies_tpu.utils.cache import enable_compilation_cache

enable_compilation_cache()

from image_analogies_tpu import SynthConfig, create_image_analogy, psnr
from image_analogies_tpu.utils.examples import super_resolution
from image_analogies_tpu.utils.kernelbench import sync as _sync
from image_analogies_tpu.utils.progress import ProgressWriter

_OUT = os.path.join(os.path.dirname(__file__), "_oracle_out")


def _cfg(size: int, matcher: str, ckpt: str = None, **kw) -> SynthConfig:
    # Same schedule as the SCALE_r04 rows.
    return SynthConfig(
        levels=6 if size > 1024 else 5, matcher=matcher, em_iters=2,
        save_level_artifacts=ckpt,
        **kw,
    )


def _cached_run(name: str, size: int, matcher: str, **kw):
    os.makedirs(_OUT, exist_ok=True)
    path = os.path.join(_OUT, f"{name}.npy")
    meta = os.path.join(_OUT, f"{name}.json")
    if os.path.exists(path) and os.path.exists(meta):
        print(f"# {name}: cached", flush=True)
        return np.load(path), json.load(open(meta))
    a, ap, b = super_resolution(size)
    a, ap, b = (jnp.asarray(x, jnp.float32) for x in (a, ap, b))
    for x in (a, ap, b):
        _sync(x)
    prog = ProgressWriter(os.path.join(_OUT, f"{name}.progress.jsonl"))
    # Per-level checkpoints: a tunnel/worker hiccup hours into the
    # 4096^2 oracle resumes from the finest completed level instead of
    # restarting (level 0 dominates, but levels 5..1 are ~20 min).
    ckpt = os.path.join(_OUT, f"{name}.ckpt")
    resume = ckpt if os.path.isdir(ckpt) else None
    t0 = time.perf_counter()
    if matcher == "brute" and size >= 2048:
        # Giant-A exact searches want the largest compiling query tile
        # (A-restream traffic is (N_B/tq) * |A|) — same override the
        # recorded 2048^2 oracle used (tools/scale_bench.py _NN_TILES).
        # The lean-brute levels already pass these tiles themselves;
        # this covers the mid-pyramid standard-path brute levels.
        from unittest import mock

        import image_analogies_tpu.kernels.nn_brute as nb

        orig = nb.exact_nn_pallas

        def big_tiles(fb, fa, **kw2):
            # ADVICE r4: gate on the database size like the lean path
            # does — tiny coarse levels must not pad small-N queries to
            # 2048-row tiles for nothing.
            if fa.shape[0] >= (1 << 20):
                kw2.setdefault("tq", 2048)
                kw2.setdefault("ta", 256)
            return orig(fb, fa, **kw2)

        # Heartbeat per query-chunk execution (~25 s apart during the
        # search): the axon tunnel can wedge a client session
        # indefinitely (observed 2026-07-31: 50 min asleep on a futex,
        # socket idle, worker healthy once the client was killed), and
        # a hung client neither crashes nor progresses — the wrapper
        # script watches this file's mtime and kills/retries on
        # staleness.
        hb = os.path.join(_OUT, "heartbeat")
        real_chunk = nb._nn_chunk_call

        # Optional per-execution budget override (element count of
        # distance-tile work per chunk — ORACLE_MAX_TILE_ELEMS=3e11
        # quarters the ~22 s level-0 executions to ~6 s).  Applied as a
        # scoped patch below (ADVICE r4: the old global mutation leaked
        # past this run).
        budget = os.environ.get("ORACLE_MAX_TILE_ELEMS")
        budget_val = None
        if budget:
            try:
                budget_val = int(float(budget))
            except ValueError:
                raise SystemExit(
                    f"ORACLE_MAX_TILE_ELEMS={budget!r} is not a number "
                    "(e.g. 3e11)"
                )

        def _beat(tag):
            try:
                with open(hb, "w") as f:
                    f.write(f"{time.time()} {tag}")
            except OSError:
                pass

        def beat_chunk(fb_chunk, fa, *a2, **k2):
            # Round-5 wedge hunt: the oracle's first level-0 chunk
            # wedged (client asleep, 0 CPU) while the SAME kernel
            # shapes ran fine as isolated probes (probe_nn_wedge.py —
            # 9.4M x 98k at 23.6 s OK), so the suspect is the eager
            # dispatch pipeline: dozens of queued executions (table
            # assembly + slices + kernels) in flight through the
            # tunnel at once.  Sync HARD on the A table before the
            # first search dispatch and on every chunk's result after
            # it — bounds the in-flight queue to ~1 execution and, via
            # the heartbeat tag, localizes any remaining wedge
            # (assembly vs search).
            _beat("pre-sync-fa")
            float(jnp.sum(fa[0, :1]))
            _beat("chunk-dispatch")
            out = real_chunk(fb_chunk, fa, *a2, **k2)
            float(jnp.asarray(out[0][0, 0]))
            _beat("chunk-done")
            return out

        import contextlib

        with contextlib.ExitStack() as stack:
            stack.enter_context(
                mock.patch.object(nb, "exact_nn_pallas", big_tiles)
            )
            stack.enter_context(
                mock.patch.object(nb, "_nn_chunk_call", beat_chunk)
            )
            if budget_val is not None:
                stack.enter_context(
                    mock.patch.object(nb, "_MAX_TILE_ELEMS", budget_val)
                )
            out = create_image_analogy(
                a, ap, b, _cfg(size, matcher, ckpt, **kw),
                progress=prog, resume_from=resume,
            )
            _sync(out)
    else:
        out = create_image_analogy(
            a, ap, b, _cfg(size, matcher, ckpt, **kw),
            progress=prog, resume_from=resume,
        )
        _sync(out)
    wall = round(time.perf_counter() - t0, 2)
    out = np.asarray(out)
    np.save(path, out)
    info = {"wall_s": wall, "matcher": matcher, "size": size, **kw}
    json.dump(info, open(meta, "w"))
    print(f"# {name}: wall {wall}s", flush=True)
    return out, info


def validate():
    """1024^2: how much does the bf16-table oracle move the metric?"""
    pm, _ = _cached_run("pm_1024", 1024, "patchmatch", pm_iters=6)
    oracle_f32, inf_f32 = _cached_run("oracle_f32_1024", 1024, "brute")
    oracle_lean, inf_lean = _cached_run(
        "oracle_lean_1024", 1024, "brute", brute_lean_bytes=1,
    )
    print(json.dumps({
        "mode": "validate-1024",
        "psnr_pm_vs_f32_oracle_db": round(psnr(pm, oracle_f32), 2),
        "psnr_pm_vs_lean_oracle_db": round(psnr(pm, oracle_lean), 2),
        "psnr_lean_vs_f32_oracle_db": round(
            psnr(oracle_lean, oracle_f32), 2
        ),
        "oracle_f32_wall_s": inf_f32["wall_s"],
        "oracle_lean_wall_s": inf_lean["wall_s"],
    }), flush=True)


def full(size: int):
    pm, pm_info = _cached_run(f"pm_{size}", size, "patchmatch", pm_iters=6)
    # 3072: force the lean-brute oracle at EVERY level (the f32 path's
    # table pair, 2 x 4.8 GB, approaches what the worker grants; its
    # recorded checkpoints were written under this cfg and resumed to
    # completion — 38.06 dB, round 5).  4096: DEFAULT budget — its
    # round-4 checkpoints (levels 5-1) were computed at the default
    # (exact f32 oracle at the sub-wall levels, the stricter metric;
    # lean-brute at levels 0-1 by the byte rule), so the default cfg
    # resumes them instead of recomputing ~30 min of pyramid; level 0
    # is lean-brute either way.
    kw = {"brute_lean_bytes": 1} if size == 3072 else {}
    # Distinct cache names per oracle mode: a default-config run at a
    # sub-3072 size runs the f32 path and must not collide with (or
    # mislabel itself as) a forced-lean run.
    name = f"oracle_lean_{size}" if size >= 3072 else f"oracle_f32_{size}"
    oracle, o_info = _cached_run(name, size, "brute", **kw)
    print(json.dumps({
        "size": size,
        "oracle": (
            "lean-brute (exact NN over bf16 lean tables)" if kw
            else (
                "brute (exact NN; f32 tables at sub-wall levels, "
                "bf16 lean tables past the byte rule)"
                if size >= 3072
                else "brute (exact NN, f32 tables)"
            )
        ),
        "psnr_vs_full_oracle_db": round(psnr(pm, oracle), 2),
        "oracle_wall_s": o_info["wall_s"],
        "pm_wall_s": pm_info["wall_s"],
    }), flush=True)


if __name__ == "__main__":
    arg = sys.argv[1] if len(sys.argv) > 1 else "validate"
    if arg == "validate":
        validate()
    else:
        full(int(arg))
