#!/usr/bin/env python
"""Compressed-candidate A/B: int8 candidate tables and/or the PCA
coarse pre-prune vs the uncompressed pipeline
(`kernels/patchmatch_tile._CAND_DTYPE` / `_CAND_PRUNE`) — the round-11
decision gate, in the tools/polish_stream_ab.py discipline.

KILL CRITERION, pre-stated: a compressed mode becomes the default iff,
on hardware at the 1024^2 headline schedule, (a) its median wall beats
the bf16/prune-off baseline's, AND (b) its min-over-seeds
PSNR-vs-oracle stays >= 35 dB with the scale probes' dist-ratio
<= 1.80.  (b) is a hard veto, not a trade axis — quality inside the
gates, then the decision rides on (a) alone: either the skipped DMA
bytes (prune) / smaller rows (int8 polish) buy wall on real HBM, or
they do not.  A loss is recorded as a negative and bf16/off stays.
Note the recorded model facts the wall must overcome: at the
headline's 4 channels the int8 SWEEP fetch is tile-granule-bound
(2C=8 int8 sublanes pad to the 32-sublane int8 tile — moved bytes
equal f32's; int8 pays at 2C >= 32, the steerable channel sets), so
the sweep-side win is the prune's, and the int8 win is the polish's.

No accelerator was reachable in round 11, so this tool is the
HARDWARE RECIPE (run on the next TPU session; QUANT_r11.json carries
the modeled projection it will confirm or kill).  On CPU the
`--verify` arm runs the measured correctness/quality cells the round
artifact quotes: default-path bit-identity (bf16/off == the module
defaults, byte-for-byte) and per-arm proxy-size quality pins
(dist-ratio vs the exact NN, PSNR vs the brute-oracle synthesis).

    python tools/quant_ab.py [size]            # TPU A/B
    python tools/quant_ab.py --verify [size]   # CPU proxy pins
"""

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax
import jax.numpy as jnp

from image_analogies_tpu.utils.cache import enable_compilation_cache

enable_compilation_cache()

from image_analogies_tpu import SynthConfig, create_image_analogy, psnr
from image_analogies_tpu.utils.examples import super_resolution
from image_analogies_tpu.utils.kernelbench import sync as _sync

# The four arms: (cand_dtype, pca_prune).  16:8 is the recipe default
# — at the 1024^2 packed C=4 geometry it models bytes/sweep at ~3.9x
# under the r7 baseline (QUANT_r11.json projection) while keeping 8 of
# 36 candidates per tile per sweep.
ARMS = (
    ("bf16", "off"),
    ("int8", "off"),
    ("bf16", "16:8"),
    ("int8", "16:8"),
)


def _set_mode(cand_dtype, prune):
    from image_analogies_tpu.kernels.patchmatch_tile import (
        set_cand_compression,
    )

    set_cand_compression(cand_dtype, prune)


def _restore_env_mode():
    _set_mode(
        os.environ.get("IA_CAND_DTYPE", "bf16"),
        os.environ.get("IA_CAND_PRUNE", "off"),
    )


def _dist_ratio(size: int, passes: int = 3) -> float:
    """Matcher-level dist-ratio vs the exact NN at the proxy size:
    `passes` tile-matcher calls (interpret mode, headline pm schedule,
    each seeding the next — the EM/pyramid warm-start the real
    synthesis provides) on assembled features of the super-resolution
    pair, final mean returned dist over mean exact dist — the SCALE
    artifacts' quality ratio, self-contained at CPU cost.  The
    uncompressed baseline measures ~1.1 here (recorded in
    QUANT_r11.json), so a compressed arm's drift is visible long
    before the 1.80 envelope."""
    from image_analogies_tpu.kernels.patchmatch_tile import (
        plan_channels,
        prepare_a_planes,
    )
    from image_analogies_tpu.models.brute import exact_nn
    from image_analogies_tpu.models.matcher import get_matcher, nnf_dist
    from image_analogies_tpu.models.patchmatch import RawPlanes
    from image_analogies_tpu.ops.features import assemble_features

    cfg = SynthConfig(
        levels=1, matcher="patchmatch", pallas_mode="interpret",
        em_iters=1, pm_iters=6, pm_polish_iters=1,
    )
    a, ap, b = super_resolution(size)
    a, ap, b = (jnp.asarray(x, jnp.float32) for x in (a, ap, b))
    f_b = assemble_features(b, b, cfg, None, None)
    f_a = assemble_features(a, ap, cfg, None, None)
    plan = plan_channels(1, 1, cfg, False, size, size, size, size)
    a_planes = prepare_a_planes(a, ap, None, None, plan[0])
    raw = RawPlanes(a, ap, None, None, a_planes)
    # The prune/int8 mode is read inside match via the module globals.
    m = get_matcher("patchmatch")
    nnf = jnp.zeros((size, size, 2), jnp.int32)
    for p in range(passes):
        nnf, _ = m.match(
            f_b, f_a, nnf, key=jax.random.PRNGKey(p), level=0, cfg=cfg,
            raw=raw,
        )
    d = f_a.shape[-1]
    # Score the RETURNED FIELD under the exact metric (nnf_dist), not
    # the matcher's reported dist: an int8 arm's reported metric is
    # computed on dequantized rows, whose quantization term biases the
    # numerator even when the assignment itself is good — the gate is
    # about match quality, so both sides of the ratio must be the same
    # exact metric.
    d_field = nnf_dist(f_b, f_a.reshape(-1, d), nnf, size)
    _, d_exact = exact_nn(
        f_b.reshape(-1, d), f_a.reshape(-1, d), chunk=4096
    )
    return float(d_field.mean()) / max(float(d_exact.mean()), 1e-30)


def verify(size: int) -> dict:
    """CPU proxy cells for QUANT_r11.json: default-path bit-identity
    plus per-arm dist-ratio and PSNR-vs-brute-oracle pins at the proxy
    size (interpret mode)."""
    a, ap, b = super_resolution(size)
    a, ap, b = (jnp.asarray(x, jnp.float32) for x in (a, ap, b))
    cfg = SynthConfig(
        levels=2, matcher="patchmatch", pallas_mode="interpret",
        em_iters=1, pm_iters=3, pm_polish_iters=1,
    )
    # Bit-identity: the module defaults ARE bf16/off — setting them
    # explicitly (through the same setter the CLI uses) must reproduce
    # the default graphs byte-for-byte.
    out_default = np.asarray(create_image_analogy(a, ap, b, cfg))
    _set_mode("bf16", "off")
    out_explicit = np.asarray(create_image_analogy(a, ap, b, cfg))
    bit_identical = bool((out_default == out_explicit).all())

    oracle = np.asarray(create_image_analogy(
        a, ap, b,
        SynthConfig(levels=2, matcher="brute", em_iters=1),
    ))
    arms = []
    for cand_dtype, prune in ARMS:
        _set_mode(cand_dtype, prune)
        out = np.asarray(create_image_analogy(a, ap, b, cfg))
        # The zero-init probe needs more passes at larger A domains
        # (the real synthesis warm-starts from the EM/pyramid): 3
        # converge 128^2, 5 converge 192^2 — measured, not tuned to
        # pass (the uncompressed baseline is held to the same gate).
        arms.append({
            "cand_dtype": cand_dtype,
            "pca_prune": prune,
            "psnr_db": round(psnr(out, oracle), 2),
            "dist_ratio_vs_exact": round(
                _dist_ratio(size, passes=3 if size <= 128 else 5), 4
            ),
        })
    _restore_env_mode()
    return {
        "arm": "verify",
        "size": size,
        "backend": "cpu-interpret",
        "default_bit_identical": bit_identical,
        "arms": arms,
        "gates": {"dist_ratio_max": 1.80, "psnr_min_db": 35.0},
    }


def measure(cand_dtype, prune, a, ap, b, oracle) -> dict:
    _set_mode(cand_dtype, prune)
    cfg = SynthConfig(
        levels=5, matcher="patchmatch", em_iters=2, pm_iters=6,
        pm_polish_iters=1,
    )
    run = lambda: create_image_analogy(a, ap, b, cfg)  # noqa: E731
    _sync(run())  # compile
    walls = []
    for _ in range(5):
        t0 = time.perf_counter()
        _sync(run())
        walls.append(round(time.perf_counter() - t0, 4))
    seeds_psnr = []
    for seed in (0, 1, 2):
        cfg_s = SynthConfig(
            levels=5, matcher="patchmatch", em_iters=2, pm_iters=6,
            pm_polish_iters=1, seed=seed,
        )
        o = np.asarray(create_image_analogy(a, ap, b, cfg_s))
        seeds_psnr.append(round(psnr(o, oracle), 2))
    return {
        "cand_dtype": cand_dtype,
        "pca_prune": prune,
        "wall_median_s": statistics.median(walls),
        "wall_runs_s": walls,
        "psnr_seeds_db": seeds_psnr,
        "psnr_min_db": min(seeds_psnr),
    }


def main():
    args = [x for x in sys.argv[1:] if x != "--verify"]
    size = int(args[0]) if args else 1024
    if "--verify" in sys.argv:
        print(json.dumps(verify(min(size, 192))), flush=True)
        return
    a, ap, b = super_resolution(size)
    a, ap, b = (jnp.asarray(x, jnp.float32) for x in (a, ap, b))
    for x in (a, ap, b):
        _sync(x)
    oracle = np.asarray(create_image_analogy(
        a, ap, b, SynthConfig(levels=5, matcher="brute", em_iters=2)
    ))
    rows = [
        measure(cand_dtype, prune, a, ap, b, oracle)
        for cand_dtype, prune in ARMS
    ]
    base = rows[0]
    res = {
        "size": size,
        "arms": rows,
        "kill_criterion": (
            "a compressed arm ships iff wall_median < the bf16/off "
            "baseline's at the 1024^2 headline AND psnr_min_db >= 35 "
            "(hard veto; dist-ratio <= 1.80 at the scale probes rides "
            "the SCALE artifact) — wall decides, quality only vetoes"
        ),
        "decision": "bf16:off",
    }
    best = base
    for row in rows[1:]:
        if (
            row["wall_median_s"] < best["wall_median_s"]
            and row["psnr_min_db"] >= 35.0
        ):
            best = row
    res["decision"] = f"{best['cand_dtype']}:{best['pca_prune']}"
    _restore_env_mode()
    print(json.dumps(res), flush=True)


if __name__ == "__main__":
    main()
