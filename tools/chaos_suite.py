#!/usr/bin/env python
"""Chaos suite — the fault x recovery matrix, run at a small proxy
size, producing the FAULTS_r12.json round artifact (round 12
tentpole).

Each arm arms one `IA_FAULT_PLAN` class (runtime/faults.py grammar)
and runs one SUPERVISED synthesis (runtime/supervisor.py) against it,
recording how the run ended:

    healed       the supervisor retried/resumed back to success with
                 the ladder never stepping — output must be
                 BIT-IDENTICAL to the undisturbed run
    degraded     the run survived only by stepping the degradation
                 ladder (recorded, never silent; the sentinel's
                 recovery check grades such a run degraded)
    clean_death  retries + ladder exhausted: SupervisorGaveUp with a
                 `check_report`-validated flight dump (the acceptance
                 bar: no fault class may end in an UNVALIDATED death)

plus the recovery overhead (arm wall vs the undisturbed supervised
wall) and the full counter ledger (retries / degradations / watchdog
breaches / injections fired), each arm's health verdict included.

Usage:
    JAX_PLATFORMS=cpu python tools/chaos_suite.py [--out FAULTS_r12.json]
        [--size 32]

tools/check_faults.py validates the artifact's schema and asserts the
no-unvalidated-death rule; tests/test_faults.py wraps both into tier-1
(the committed artifact) with the matrix itself slow-marked per the
round-8 budget rule.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import tempfile
import time

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
)

FAULTS_SCHEMA_VERSION = 1

# The matrix: every IA_FAULT_PLAN action class at every engine
# injection point family, plus the ladder and clean-death arms.
# (plan, supervise-kwargs, expect) triples; `hang` uses tiny watchdog
# bounds so the proxy run breaches in milliseconds, not the production
# 900 s static bound.
_TINY_WATCHDOG = dict(
    static_deadline_s=2.0, min_deadline_s=0.2, watchdog_slack=2.0
)


def _arms():
    return [
        dict(name="level_raise", plan="level:0:raise", kw={},
             expect="healed"),
        dict(name="kernel_raise", plan="kernel:0:raise", kw={},
             expect="healed"),
        dict(name="level_hang_watchdog", plan="level:0:hang:60",
             kw=dict(_TINY_WATCHDOG), expect="healed"),
        dict(name="ckpt_truncate", plan="ckpt:1:truncate,level:0:raise",
             kw={}, expect="healed"),
        dict(name="xfer_fail", plan="xfer:0:fail", kw={},
             expect="healed"),
        dict(name="ladder_degrade", plan="level:0:raise:3",
             kw=dict(max_retries=1), expect="degraded"),
        dict(name="clean_death", plan="level:1:raise:99",
             kw=dict(max_retries=0, ladder=[]), expect="clean_death"),
    ]


def _proxy_inputs(size: int):
    import numpy as np

    rng = np.random.default_rng(0)
    a = rng.random((size, size)).astype(np.float32)
    ap = np.clip(a * 0.5 + 0.2, 0, 1).astype(np.float32)
    b = rng.random((size, size)).astype(np.float32)
    return a, ap, b


def _snapshot_modes():
    """Capture every process-wide seam a ladder step may flip, so each
    arm can be restored to the CALLER'S configuration (which may be a
    non-default env arm: IA_CAND_DTYPE=int8, IA_POLISH_MODE=stream,
    IA_A_PLANE_LAYOUT=unpacked) — not to hard-coded defaults."""
    from image_analogies_tpu.kernels import patchmatch_tile as pt
    from image_analogies_tpu.models import patchmatch as pm

    prune = pt.resolve_prune()
    return {
        "packed": pt.resolve_packed(),
        "polish": pm._POLISH_MODE,
        "cand_dtype": pt.resolve_cand_dtype(),
        "prune": "off" if prune is None else f"{prune[0]}:{prune[1]}",
    }


def _restore_modes(snap):
    """Reset every process-wide seam a ladder step may have flipped —
    arms must not leak state into each other (or into the caller).
    Each setter is invoked only on an actual difference: the cand
    setter clears ALL compiled caches unconditionally, and a no-op
    clear after every arm would recompile the whole proxy pipeline."""
    from image_analogies_tpu.kernels.patchmatch_tile import (
        set_cand_compression,
        set_packed_layout,
    )
    from image_analogies_tpu.models.patchmatch import set_polish_mode
    from image_analogies_tpu.runtime.faults import set_fault_plan

    set_fault_plan(None)
    set_packed_layout("packed" if snap["packed"] else "unpacked")
    set_polish_mode(snap["polish"])
    now = _snapshot_modes()
    if (now["cand_dtype"], now["prune"]) != (
        snap["cand_dtype"], snap["prune"]
    ):
        set_cand_compression(snap["cand_dtype"], snap["prune"])


def run_chaos(size: int = 32):
    """Run the matrix; returns the FAULTS record (not yet written)."""
    import numpy as np

    from image_analogies_tpu import SynthConfig, create_image_analogy
    from image_analogies_tpu.runtime import faults, supervisor
    from image_analogies_tpu.telemetry import MetricsRegistry, Tracer
    from image_analogies_tpu.telemetry.flight import FlightRecorder
    from image_analogies_tpu.telemetry.metrics import set_registry
    from image_analogies_tpu.telemetry.sentinel import evaluate_health

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from check_report import validate_flight

    a, ap, b = _proxy_inputs(size)
    cfg0 = SynthConfig(
        levels=2, matcher="patchmatch", em_iters=2, pm_iters=3
    )
    # The undisturbed oracle + compile warm-up (shared jit caches make
    # every arm's wall a retry/recovery measurement, not a compile
    # one).
    bp_ref = np.asarray(create_image_analogy(a, ap, b, cfg0))

    def one_supervised(plan, **kw):
        ckpt = tempfile.mkdtemp(prefix="ia_chaos_ckpt_")
        flight_dir = tempfile.mkdtemp(prefix="ia_chaos_flight_")
        cfg = dataclasses.replace(cfg0, save_level_artifacts=ckpt)
        reg = MetricsRegistry()
        prev = set_registry(reg)
        tracer = Tracer(registry=reg)
        rec = FlightRecorder(
            tracer, reg, os.path.join(flight_dir, "flight.json")
        )
        rec.install()
        tracer.flight_recorder = rec
        faults.set_fault_plan(plan)
        out = err = None
        t0 = time.perf_counter()
        try:
            out = supervisor.supervise(
                lambda resume: create_image_analogy(
                    a, ap, b, cfg, progress=tracer, resume_from=resume
                ),
                ckpt_dir=ckpt, tracer=tracer, backoff_s=0.0, **kw,
            )
        except supervisor.SupervisorGaveUp as e:
            err = e
        wall = time.perf_counter() - t0
        faults.set_fault_plan(None)
        rec.uninstall()
        set_registry(prev)
        flight_path = os.path.join(flight_dir, "flight.json")
        flight = None
        if os.path.exists(flight_path):
            with open(flight_path) as f:
                flight = json.load(f)
        health = evaluate_health(
            spans=tracer.to_dict(), metrics=reg.to_dict(),
            context="chaos",
        )
        return out, err, reg, wall, flight, health

    def counter_total(reg, name):
        return sum(reg.counter(name, "")._values.values())

    mode_snap = _snapshot_modes()
    # Baseline: a supervised run with NO faults (same forced-checkpoint
    # config) — the denominator for each arm's recovery overhead.
    out, err, _, base_wall, _, _ = one_supervised(None)
    assert err is None and np.array_equal(np.asarray(out), bp_ref), (
        "undisturbed supervised run must heal-free reproduce the "
        "oracle"
    )
    _restore_modes(mode_snap)

    arms_out = []
    classes = set()
    for arm in _arms():
        out, err, reg, wall, flight, health = one_supervised(
            arm["plan"], **arm["kw"]
        )
        degradations = counter_total(reg, "ia_degradations_total")
        if err is not None:
            outcome = "clean_death"
        elif degradations:
            outcome = "degraded"
        else:
            outcome = "healed"
        bit_identical = (
            bool(np.array_equal(np.asarray(out), bp_ref))
            if out is not None else None
        )
        rec_check = next(
            c for c in health["checks"] if c["name"] == "recovery"
        )
        arms_out.append({
            "name": arm["name"],
            "fault_plan": arm["plan"],
            "expected_outcome": arm["expect"],
            "outcome": outcome,
            "bit_identical": bit_identical,
            "retries": counter_total(reg, "ia_retries_total"),
            "degradations": degradations,
            "watchdog_breaches": counter_total(
                reg, "ia_watchdog_breaches_total"
            ),
            "injections_fired": counter_total(
                reg, "ia_fault_injections_total"
            ),
            "recovery_overhead_frac": round(
                max(0.0, wall / base_wall - 1.0), 4
            ),
            "flight_flushed_on": (
                flight.get("flushed_on") if flight else None
            ),
            "flight_validated": (
                validate_flight(flight) == [] if flight else False
            ),
            "gave_up": err is not None,
            "health_verdict": health["verdict"],
            "recovery_check": rec_check["status"],
        })
        for act in ("raise", "hang", "truncate", "fail"):
            if f":{act}" in arm["plan"]:
                classes.add(act)
        if err is not None:
            classes.add("clean_death")
        _restore_modes(mode_snap)

    return {
        "schema_version": FAULTS_SCHEMA_VERSION,
        "kind": "faults",
        "round": 12,
        "generated_by": "tools/chaos_suite.py",
        "proxy_size": size,
        "config": {
            "levels": 2, "matcher": "patchmatch", "em_iters": 2,
            "pm_iters": 3,
        },
        "baseline_supervised_wall_s": round(base_wall, 3),
        "classes_covered": sorted(classes),
        "arms": arms_out,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="FAULTS_r12.json")
    ap.add_argument("--size", type=int, default=32)
    args = ap.parse_args(argv)
    record = run_chaos(args.size)
    with open(args.out, "w") as f:
        json.dump(record, f, indent=1)
        f.write("\n")
    n_bad = sum(
        1 for arm in record["arms"]
        if arm["outcome"] != arm["expected_outcome"]
    )
    for arm in record["arms"]:
        print(
            f"{arm['name']:>22}: {arm['outcome']:<11} "
            f"(expected {arm['expected_outcome']}; retries "
            f"{arm['retries']:.0f}, degr {arm['degradations']:.0f}, "
            f"breaches {arm['watchdog_breaches']:.0f}, overhead "
            f"{arm['recovery_overhead_frac']:.2f})"
        )
    print(f"wrote {args.out}")
    return 1 if n_bad else 0


if __name__ == "__main__":
    sys.exit(main())
