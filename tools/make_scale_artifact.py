"""Assemble SCALE_r{N}.json from a tools/scale_bench.py log.

Usage: python tools/make_scale_artifact.py <log> <out.json>
Takes the last complete set of size rows from the log (one JSON object
per line) and wraps them with the artifact comment.
"""

import json
import sys

COMMENT = (
    "Large-image scaling rows, tools/scale_bench.py, TPU v5e-1, "
    "2026-07-31, round-4 HBM-streaming kernel (no banding at any size, "
    "full channel set everywhere).  Quality: <=2048^2 rows carry PSNR "
    "vs the FULL-SYNTHESIS exact-NN oracle (brute synthesis at every "
    "level/EM step — the round-3 'reproducibly crashes the TPU worker' "
    "blocker is fixed by per-execution work budgeting: "
    "kernels/nn_brute.py _MAX_TILE_ELEMS + models/analogy.py "
    "_SAFE_EXEC_DIST_ELEMS), plus a stratified-jittered exact probe "
    "(1M pixels or half the image, bootstrap 95% CI on the "
    "achieved/exact mean-distance ratio, exact-match fraction) in the "
    "lean bf16 metric at the EM fixed point.  The 4096^2 full oracle "
    "would be ~16x the 2048^2 one's 880 s; its row is bounded by the "
    "probe, calibrated by the 1024^2/2048^2 rows where both metrics "
    "exist (ratio 1.496 ~ 35.69 dB, 1.597 ~ 35.24 dB)."
)


def main():
    log, out = sys.argv[1], sys.argv[2]
    rows = {}
    for line in open(log):
        line = line.strip()
        if line.startswith("{"):
            row = json.loads(line)
            rows[row["size"]] = row  # last one per size wins
    with open(out, "w") as f:
        json.dump(
            {"comment": COMMENT, "rows": [rows[k] for k in sorted(rows)]},
            f, indent=1,
        )
        f.write("\n")
    print(f"wrote {out} with sizes {sorted(rows)}")


if __name__ == "__main__":
    main()
