"""Recorded kernel tuning pass over TILE_H and the K_* candidate budget
(round-2 VERDICT task 3: "any K/TILE change is justified by a measured
before/after").

Monkeypatches the module constants, re-derives the plan, and measures
steady-state tile_sweep time at the headline 1024^2 geometry plus an
end-to-end 1024^2 synthesis wall for each variant.  Results print as
JSON lines; the chosen configuration is recorded in README.md's kernel
section.

Run on the TPU box:  python tools/tune_kernel.py
"""

import itertools
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax
import jax.numpy as jnp

from image_analogies_tpu.utils.cache import enable_compilation_cache

enable_compilation_cache()

from image_analogies_tpu import SynthConfig, create_image_analogy
from image_analogies_tpu.utils.examples import super_resolution
import image_analogies_tpu.kernels.patchmatch_tile as pt
import image_analogies_tpu.models.analogy as an
from image_analogies_tpu.utils.kernelbench import sync as _sync

# Module defaults captured at import: the baseline row measures THESE,
# and the final restore puts them back (hardcoding a historical config
# here would silently leave callers on the wrong constants after a
# retune).
_DEFAULTS = (pt.TILE_H, pt.K_OWN, pt.K_PROP, pt.K_LOCAL, pt.K_GLOBAL)


def set_constants(tile_h=None, k_own=None, k_prop=None, k_local=None,
                  k_global=None):
    """Patch the kernel's static constants and keep derived ones in sync."""
    if tile_h is not None:
        pt.TILE_H = tile_h
    if k_own is not None:
        pt.K_OWN = k_own
    if k_prop is not None:
        pt.K_PROP = k_prop
    if k_local is not None:
        pt.K_LOCAL = k_local
    if k_global is not None:
        pt.K_GLOBAL = k_global
    pt.K_TOTAL = pt.K_OWN + pt.K_PROP + pt.K_LOCAL + pt.K_GLOBAL
    pt.K_COHERENT = pt.K_OWN + pt.K_PROP
    # Cached compiled level fns bake the old constants in — drop them.
    an._level_fn.cache_clear()
    an._em_step_fn.cache_clear()


def sweep_time(cfg, size=1024, iters=16):
    """Steady-state all-bands tile_sweep ms at the headline geometry
    (shared harness: utils/kernelbench.py)."""
    from image_analogies_tpu.utils.kernelbench import sweep_time_ms

    timed = sweep_time_ms(cfg, size, iters)
    if timed is None:
        return None
    ms, meta = timed
    return round(ms, 3), meta["n_bands"]


def end_to_end(cfg, a, ap, b, runs=3):
    _sync(create_image_analogy(a, ap, b, cfg))
    walls = []
    for _ in range(runs):
        t0 = time.perf_counter()
        _sync(create_image_analogy(a, ap, b, cfg))
        walls.append(time.perf_counter() - t0)
    return round(min(walls), 3)


def psnr_probe(cfg, a, ap, b, oracle):
    from image_analogies_tpu import psnr

    out = create_image_analogy(a, ap, b, cfg)
    return round(psnr(np.asarray(out), oracle), 2)


def main():
    size = 1024
    cfg = SynthConfig(levels=5, matcher="patchmatch", em_iters=2, pm_iters=6)
    a, ap, b = super_resolution(size)
    a, ap, b = (jnp.asarray(x, jnp.float32) for x in (a, ap, b))
    for x in (a, ap, b):
        _sync(x)
    oracle = np.asarray(
        create_image_analogy(
            a, ap, b, SynthConfig(levels=5, matcher="brute", em_iters=2)
        )
    )

    variants = [
        # (label, tile_h, k_own, k_prop, k_local, k_global)
        # Constraints: K_OWN a perfect square (the jittered subgrid is
        # side x side), K_PROP <= 4*K_OWN and divisible by 4 (neighbor
        # tiles donate their first K_PROP//4 own samples).
        ("module default " + "/".join(map(str, _DEFAULTS)), *_DEFAULTS),
        ("r2 baseline t64 k16/16/12/4", 64, 16, 16, 12, 4),
        ("t32", 32, 16, 16, 12, 4),
        ("t96", 96, 16, 16, 12, 4),
        ("k-small 4/8/8/4", 64, 4, 8, 8, 4),
        ("k-large 16/16/20/8", 64, 16, 16, 20, 8),
        ("k-prop-heavy 4/16/12/4", 64, 4, 16, 12, 4),
    ]
    for label, th, ko, kp, kl, kg in variants:
        set_constants(th, ko, kp, kl, kg)
        rec = None
        for attempt in range(2):  # tunnel compiles flake; retry once
            try:
                st = sweep_time(cfg, size)
                wall = end_to_end(cfg, a, ap, b)
                q = psnr_probe(cfg, a, ap, b, oracle)
                rec = {
                    "variant": label, "tile_h": th,
                    "k": [ko, kp, kl, kg],
                    "sweep_ms": st[0] if st else None,
                    "n_bands": st[1] if st else None,
                    "wall_s": wall, "psnr_db": q,
                }
                break
            except Exception as e:  # noqa: BLE001 - record and continue
                rec = {"variant": label, "error": str(e)[:200]}
        print(json.dumps(rec), flush=True)
    set_constants(*_DEFAULTS)  # restore module defaults


if __name__ == "__main__":
    main()
