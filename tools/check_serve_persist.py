#!/usr/bin/env python
"""Validate a SERVE_r18.json persistent-cache / pipelined-dispatch
artifact (round 18).

The round-18 acceptance bar, enforced by a validator instead of
trusted to prose:

  - RESTART arm: a fresh process over a populated disk executable
    cache must answer its FIRST request >= 10x faster than the
    cold-compile path (`cold_ms >= 10 * cold_restart_ms`), the first
    request's verdict must be `disk` (it ran deserialized executables,
    not a recompile), its response must be BIT-IDENTICAL to the
    fresh-compile response (`bit_identical` pins the sha256 pair),
    zero disk errors, and the disk counters must reconcile with the
    in-memory cache (disk hits + disk misses == in-memory misses).
  - PIPELINE arm: the concurrent burst through a window > 1 must stay
    bit-identical to solo dispatch (the round-13 isolation contract),
    its ledger must balance (requests == admitted + shed; completed +
    failed + shed == requests when nothing was cancelled), p50 <= p99,
    and the occupancy gauge must have returned to zero.
  - Both arms' final registries must grade `ok` under the sentinel's
    own serving check — an artifact the daemon's invariants reject is
    a bug report, not a benchmark.

Usage:
    python tools/check_serve_persist.py SERVE_r18.json

Runs under pytest too (tests/test_serving_persist.py validates the
COMMITTED artifact) so tier-1 fails if the record is missing,
truncated, or structurally degraded.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List

SERVE_PERSIST_SCHEMA_VERSION = 1
RESTART_SPEEDUP_MIN = 10.0


def _num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def validate_serve_persist(record: dict) -> List[str]:
    """Return a list of violations (empty = valid)."""
    errs: List[str] = []
    if not isinstance(record, dict):
        return ["record is not a JSON object"]
    if record.get("schema_version") != SERVE_PERSIST_SCHEMA_VERSION:
        errs.append(
            f"schema_version {record.get('schema_version')!r} != "
            f"{SERVE_PERSIST_SCHEMA_VERSION}"
        )
    if record.get("kind") != "serve_persist":
        errs.append(f"kind {record.get('kind')!r} != 'serve_persist'")
    size = record.get("proxy_size")
    if not (_num(size) and size >= 16):
        errs.append(f"proxy_size {size!r} is not a size >= 16")

    # ------------------------------------------------- restart arm
    p = record.get("persist")
    if not isinstance(p, dict):
        errs.append("persist: missing object")
        p = {}
    cold = p.get("cold_ms")
    restart = p.get("cold_restart_ms")
    warm = p.get("warm_ms")
    if not (_num(cold) and cold > 0):
        errs.append(f"persist.cold_ms {cold!r} is not > 0")
    if not (_num(restart) and restart > 0):
        errs.append(
            f"persist.cold_restart_ms {restart!r} is not > 0"
        )
    if _num(cold) and _num(restart) and \
            cold < RESTART_SPEEDUP_MIN * restart:
        errs.append(
            f"persist: cold_ms {cold} < {RESTART_SPEEDUP_MIN:.0f}x "
            f"cold_restart_ms {restart} — the restart-with-populated-"
            "disk-cache first request must be >= 10x faster than the "
            "cold compile (the tentpole's acceptance gate)"
        )
    if not (_num(warm) and warm > 0):
        errs.append(f"persist.warm_ms {warm!r} is not > 0")
    elif _num(cold) and cold < RESTART_SPEEDUP_MIN * warm:
        errs.append(
            f"persist: cold_ms {cold} < {RESTART_SPEEDUP_MIN:.0f}x "
            f"warm_ms {warm} — the in-memory hit after the restore "
            "must beat the cold compile at least as hard as the "
            "restore did"
        )
    if p.get("first_restart_cache") != "disk":
        errs.append(
            f"persist.first_restart_cache "
            f"{p.get('first_restart_cache')!r} != 'disk' — the "
            "restarted daemon's first request must run deserialized "
            "executables, not recompile"
        )
    if p.get("bit_identical") is not True:
        errs.append(
            "persist.bit_identical is not true — the restored "
            "executable's response must match the fresh-compile "
            "response byte for byte"
        )
    if not (_num(p.get("restore_ms")) and p["restore_ms"] >= 0):
        errs.append(
            f"persist.restore_ms {p.get('restore_ms')!r} is not a "
            "non-negative number"
        )
    disk = p.get("disk")
    if not isinstance(disk, dict):
        errs.append("persist.disk: missing object")
        disk = {}
    for k in ("hits", "misses", "errors"):
        if not (_num(disk.get(k)) and disk.get(k) >= 0):
            errs.append(
                f"persist.disk.{k} {disk.get(k)!r} is not a "
                "non-negative number"
            )
    if _num(disk.get("errors")) and disk["errors"] != 0:
        errs.append(
            f"persist.disk.errors {disk['errors']} != 0 — the restart "
            "arm must restore cleanly (corrupt-blob handling is the "
            "test suite's job, not the benchmark's)"
        )
    mem_misses = p.get("cache_misses")
    if all(_num(v) for v in (disk.get("hits"), disk.get("misses"),
                             mem_misses)):
        if disk["hits"] + disk["misses"] != mem_misses:
            errs.append(
                f"persist: disk hits {disk['hits']} + disk misses "
                f"{disk['misses']} != in-memory misses {mem_misses} — "
                "the disk tier must be probed exactly once per "
                "in-memory miss"
            )
    else:
        errs.append(
            "persist: disk.hits/disk.misses/cache_misses must all be "
            "numbers (the reconciliation ledger)"
        )
    if p.get("serving_check") != "ok":
        errs.append(
            f"persist.serving_check {p.get('serving_check')!r} != "
            "'ok'"
        )

    # ------------------------------------------------ pipeline arm
    pl = record.get("pipeline")
    if not isinstance(pl, dict):
        errs.append("pipeline: missing object")
        pl = {}
    win = pl.get("window")
    if not (_num(win) and win > 1):
        errs.append(
            f"pipeline.window {win!r} is not > 1 — the pipeline arm "
            "must actually open the in-flight window"
        )
    if pl.get("bit_identical") is not True:
        errs.append(
            "pipeline.bit_identical is not true — pipelined responses "
            "must match solo dispatch byte for byte (the round-13 "
            "isolation contract is the pin)"
        )
    if not (_num(pl.get("requests")) and pl["requests"] >= 2):
        errs.append(
            f"pipeline.requests {pl.get('requests')!r} is not a "
            "count >= 2"
        )
    p50, p99 = pl.get("p50_warm_ms"), pl.get("p99_warm_ms")
    if not (_num(p50) and _num(p99)):
        errs.append(
            f"pipeline.p50_warm_ms/p99_warm_ms {p50!r}/{p99!r} must "
            "be numbers"
        )
    elif p50 > p99:
        errs.append(f"pipeline: p50_warm_ms {p50} > p99_warm_ms {p99}")
    if pl.get("inflight_batches_after") != 0:
        errs.append(
            f"pipeline.inflight_batches_after "
            f"{pl.get('inflight_batches_after')!r} != 0 — the "
            "occupancy gauge must return to zero at quiescence"
        )
    ledger = pl.get("ledger")
    if not isinstance(ledger, dict):
        errs.append("pipeline.ledger: missing object")
        ledger = {}
    if all(_num(ledger.get(k)) for k in ("requests", "admitted",
                                         "shed")):
        if ledger["requests"] != ledger["admitted"] + ledger["shed"]:
            errs.append(
                f"pipeline.ledger: requests {ledger['requests']} != "
                f"admitted {ledger['admitted']} + shed "
                f"{ledger['shed']}"
            )
    else:
        errs.append(
            "pipeline.ledger: requests/admitted/shed must be numbers"
        )
    if all(_num(ledger.get(k)) for k in ("admitted", "completed",
                                         "failed")):
        if ledger["admitted"] != ledger["completed"] + \
                ledger["failed"]:
            errs.append(
                f"pipeline.ledger: admitted {ledger['admitted']} != "
                f"completed {ledger['completed']} + failed "
                f"{ledger['failed']}"
            )
    else:
        errs.append(
            "pipeline.ledger: admitted/completed/failed must be "
            "numbers"
        )
    if all(_num(ledger.get(k)) for k in ("hits", "misses",
                                         "dispatches")):
        if ledger["hits"] + ledger["misses"] != ledger["dispatches"]:
            errs.append(
                f"pipeline.ledger: hits {ledger['hits']} + misses "
                f"{ledger['misses']} != dispatches "
                f"{ledger['dispatches']} — every dispatch consults "
                "the cache exactly once, window open or not"
            )
    else:
        errs.append(
            "pipeline.ledger: hits/misses/dispatches must be numbers"
        )
    if pl.get("serving_check") != "ok":
        errs.append(
            f"pipeline.serving_check {pl.get('serving_check')!r} != "
            "'ok'"
        )
    return errs


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="SERVE_r18.json to validate")
    args = ap.parse_args(argv)
    try:
        with open(args.path) as f:
            record = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"check_serve_persist: cannot read {args.path}: {e}")
        return 1
    errs = validate_serve_persist(record)
    if errs:
        print(f"check_serve_persist: {args.path} INVALID:")
        for e in errs:
            print(f"  - {e}")
        return 1
    p = record.get("persist", {})
    pl = record.get("pipeline", {})
    print(
        f"check_serve_persist: {args.path} OK (cold "
        f"{p.get('cold_ms')} ms -> restart {p.get('cold_restart_ms')} "
        f"ms, {p.get('restart_speedup')}x; pipeline window "
        f"{pl.get('window')} p99 warm {pl.get('p99_warm_ms')} ms, "
        "bit-identical both arms)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
