#!/usr/bin/env python
"""Bench-trajectory regression detector over the committed
`BENCH_r<NN>.json` / `SCALE_r<NN>.json` history (ISSUE 4 tentpole,
part b).

Eight rounds of benchmark artifacts encode the project's performance
trajectory, but nothing machine-checked it: a silent 2x regression in
sweep bytes, wall, or quality between rounds would only be caught by a
human rereading JSON.  This tool declares the tracked series WITH
their tolerances (the table ARCHITECTURE.md quotes) and fails loudly
when a later round's MEASURED cell is worse than the best prior
measured cell beyond its tolerance, or breaks an absolute floor or
ceiling.

Provenance discipline: a cell may be marked carried or modeled —
either a row/record-level `"provenance": "carried"|"modeled"` or a
per-field `"cell_provenance": {"<field>": "carried"}` (absent means
measured, which is true of every artifact committed before round 9).
Carried/modeled cells are schema-validated and reported but NEVER
enter the regression comparison and NEVER become the trajectory's
best: a carried cell can not "improve" a trajectory, and a projection
can not set the bar a later measurement is judged against.

Series declarations carry a `since` round: series whose measurement
methodology stabilized later (the round-3 timing revision, the round-4
HBM-streaming traffic model) start there, so the checker holds history
to the rules each era actually obeyed.  Moving a `since` forward is an
explicit, reviewable act — exactly the loud failure this tool exists
to force when a model legitimately changes.

Schema checks are round-aware too: every BENCH record answers the
headline questions; round >= 3 records need their acceptance table;
roofline fractions are held to [0, 1] whenever present; round >= 9
records must pass the FULL current validator (tools/check_bench.py),
including the embedded run-sentinel health verdict bench.py now ships.

Usage:
    python tools/check_trajectory.py --all           # repo history
    python tools/check_trajectory.py --all --root DIR
    python tools/check_trajectory.py --json OUT.json --all

Exit codes: 0 trajectory holds, 1 violation(s), 2 unreadable input.
Runs under pytest (tests/test_trajectory.py) so tier-1 fails if any
committed artifact violates its own schema or the declared tolerances.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

_BENCH_RE = re.compile(r"^BENCH_r(\d+)\.json$")
_SCALE_RE = re.compile(r"^SCALE_r(\d+)\.json$")
_VIDEO_RE = re.compile(r"^VIDEO_r(\d+)\.json$")
_SLO_RE = re.compile(r"^SLO_r(\d+)\.json$")
_CHAOS_SERVE_RE = re.compile(r"^CHAOS_SERVE_r(\d+)\.json$")
_MESH2D_RE = re.compile(r"^MESH2D_r(\d+)\.json$")
# SERVE_r<NN>.json is shared by two kinds: the round-13 load-sweep
# records (kind "serve") and the round-18 persistent-cache records
# (kind "serve_persist").  load_history disambiguates on the kind
# field — the filename round number alone is not the discriminator.
_SERVE_PERSIST_RE = re.compile(r"^SERVE_r(\d+)\.json$")
_OBS_RE = re.compile(r"^OBS_r(\d+)\.json$")
_LATTICE_RE = re.compile(r"^LATTICE_r(\d+)\.json$")
_ROUTER_RE = re.compile(r"^ROUTER_r(\d+)\.json$")
_TRACE_RE = re.compile(r"^TRACE_r(\d+)\.json$")
_ARCHIVE_RE = re.compile(r"^ARCHIVE_r(\d+)\.json$")

PROVENANCES = ("measured", "carried", "modeled")

# ---------------------------------------------------------- declarations
# direction: "lower"/"higher" is better.  rel_tol/abs_tol: a later
# measured cell may be worse than the best prior measured cell by at
# most this much (either bound passing suffices when both are given).
# floor/ceiling: absolute bounds on every measured cell.  since: first
# round the series' methodology holds (see module docstring).
BENCH_SERIES: Tuple[Dict, ...] = (
    {"field": "value", "direction": "lower", "rel_tol": 0.15,
     "since": 3, "label": "1024^2 headline wall (s)"},
    {"field": "value_default_schedule_s", "direction": "lower",
     "rel_tol": 0.10, "since": 3,
     "label": "default-schedule wall (s)"},
    {"field": "psnr_vs_cpu_ref_db", "direction": "higher",
     "abs_tol": 0.30, "floor": 35.0, "since": 3,
     "label": "min-seed PSNR vs exact oracle (dB)"},
    {"field": "kernel_sweep_ms", "direction": "lower", "rel_tol": 0.25,
     "since": 3, "label": "tile_sweep steady-state (ms)"},
    {"field": "kernel_bytes_per_sweep", "direction": "lower",
     "rel_tol": 0.02, "since": 4,
     "label": "modeled sweep traffic (B; r4 streaming model)"},
    {"field": "kernel_hbm_roofline_frac", "direction": "higher",
     "rel_tol": 0.20, "since": 4, "label": "HBM roofline fraction"},
    {"field": "instrumented_wall_s", "direction": "lower",
     "rel_tol": 0.20, "since": 4,
     "label": "instrumented-run wall (s; telemetry overhead proxy)"},
    {"field": "kernel_candidate_dma_efficiency", "direction": "higher",
     "abs_tol": 0.05, "since": 7,
     "label": "candidate-DMA useful/moved fraction"},
    {"field": "kernel_polish_dma_efficiency", "direction": "higher",
     "abs_tol": 0.05, "since": 8,
     "label": "polish-DMA useful/moved fraction"},
    {"field": "kernel_bytes_per_polish", "direction": "lower",
     "rel_tol": 0.02, "since": 8, "label": "modeled polish traffic (B)"},
)

# VIDEO artifacts (round 14: tools/video_bench.py) are nested records;
# load_history flattens the tracked cells (`_flatten_video`) so the
# same provenance discipline applies: a modeled warm_cost_ratio can
# never set the bar a later measured one is judged against.
VIDEO_SERIES: Tuple[Dict, ...] = (
    {"field": "flicker_warm_tau", "direction": "lower", "rel_tol": 0.50,
     "since": 14,
     "label": "stylized-output flicker with the coherence term"},
    {"field": "warm_cost_ratio", "direction": "lower", "rel_tol": 0.15,
     "ceiling": 0.6, "since": 14,
     "label": "modeled warm/cold schedule cost ratio"},
    {"field": "quality_mean_delta_db", "direction": "higher",
     "abs_tol": 0.30, "floor": -0.1, "since": 14,
     "label": "warm-vs-cold PSNR-vs-oracle delta (dB)"},
)

# SLO artifacts (round 15: tools/serve_load.py --slo-out) carry the
# serving tier's headline objectives at top level.  The latency series
# is held LOOSELY (rel_tol 0.5): the committed sweep runs a CPU proxy
# under pytest on shared machines, so only a multiple-of-itself
# regression is a signal; availability is the tight series (the retry
# ladder should absorb faults — a committed record below 0.95 means
# the serving tier lost requests).
SLO_SERIES: Tuple[Dict, ...] = (
    {"field": "p99_warm_ms", "direction": "lower", "rel_tol": 0.50,
     "since": 15, "label": "serving warm p99 latency (ms; CPU proxy)"},
    {"field": "availability", "direction": "higher", "abs_tol": 0.02,
     "floor": 0.95, "since": 15,
     "label": "serving availability over admitted requests"},
)

# CHAOS_SERVE artifacts (round 16: tools/chaos_serve.py) carry the
# serving-resilience headline at top level.  acked_loss and
# replay_bit_identical are ABSOLUTE invariants (ceiling/floor, no
# drift allowed — losing one acknowledged request, or replaying one
# request differently, is a broken tier, not a regression trend);
# recovery_warm_ms is held loosely like the SLO latency series (CPU
# proxy on shared machines: only a multiple-of-itself slowdown in
# kill -> takeover -> fully-replayed is a signal).
CHAOS_SERVE_SERIES: Tuple[Dict, ...] = (
    {"field": "acked_loss", "direction": "lower", "abs_tol": 0.0,
     "ceiling": 0.0, "since": 16,
     "label": "acked requests lost across kill -> takeover"},
    {"field": "recovery_warm_ms", "direction": "lower", "rel_tol": 1.0,
     "since": 16,
     "label": "kill -> takeover full-recovery wall (ms; CPU proxy)"},
    {"field": "replay_bit_identical", "direction": "higher",
     "abs_tol": 0.0, "floor": 1.0, "since": 16,
     "label": "takeover replay bit-identity (1.0 = every replay)"},
)

# MESH2D artifacts (round 17: tools/scale_bench.py --mesh2d) carry
# size-keyed rows like SCALE.  The wall series is held LOOSELY
# (rel_tol 1.0): committed rows so far are interpret-mode CPU proxies
# on shared machines, so only a multiple-of-itself slowdown signals.
# The modeled 8192^2/16384^2/32768^2 projections ride in the same rows
# under `provenance: "modeled"` — the standard discipline makes them
# inert here (listed, never a bar), and tools/check_mesh2d.py
# separately re-prices each from its recorded inputs.
MESH2D_SERIES: Tuple[Dict, ...] = (
    {"field": "wall_s", "direction": "lower", "rel_tol": 1.0,
     "since": 17, "label": "2-D mesh warm wall (s; CPU proxy so far)"},
    {"field": "wall_1d_same_slabs_s", "direction": "lower",
     "rel_tol": 1.0, "since": 17,
     "label": "1-D same-slab-count reference wall (s)"},
)

# SERVE_PERSIST artifacts (round 18: tools/serve_load.py
# --persist-out) carry the serving cold-start headline.  Both series
# are held LOOSELY (rel_tol 1.0) like the other CPU-proxy serving
# walls: the committed record is measured under pytest on shared
# machines, so only a multiple-of-itself slowdown is a signal.  The
# hard 10x restart gate is NOT re-derived here — check_serve_persist
# enforces it on every record's own cold_ms/cold_restart_ms pair;
# this table only watches the trend across rounds.
SERVE_PERSIST_SERIES: Tuple[Dict, ...] = (
    {"field": "cold_restart_ms", "direction": "lower", "rel_tol": 1.0,
     "since": 18,
     "label": "restart-with-populated-disk first request (ms; CPU "
              "proxy)"},
    {"field": "p99_warm_ms", "direction": "lower", "rel_tol": 1.0,
     "since": 18,
     "label": "pipelined-dispatch warm p99 (ms; CPU proxy)"},
)

# OBS artifacts (round 19: tools/serve_load.py --obs-out) carry the
# serving observatory's measured request-path overhead at top level.
# The ceiling is the HARD telemetry budget the sentinel watches
# (`ia_observatory_overhead_frac` vs OVERHEAD_BUDGET_FRAC): a
# committed record at or past 2% means the observation plane itself
# became a serving regression.  The trend is held loosely (rel_tol
# 1.0 + abs_tol 0.01: min-paired-delta clamps to 0.0 when the paired
# arms tie, and a literal-zero best would otherwise make ANY later
# positive measurement a "regression"); the absolute ceiling is the
# real gate (check_obs enforces it per record too; this table watches
# the trend AND re-states the bound so a future checker edit cannot
# silently drop it from history).
OBS_SERIES: Tuple[Dict, ...] = (
    {"field": "observatory_overhead_frac", "direction": "lower",
     "rel_tol": 1.0, "abs_tol": 0.01, "ceiling": 0.02, "since": 19,
     "label": "observatory request-path overhead fraction"},
)

# LATTICE artifacts (round 20: tools/serve_load.py --lattice-out)
# carry the shape-lattice admission headline at top level: the
# never-seen-shape-burst p99 over the warm p99.  The 2.0 ceiling IS
# the acceptance criterion (cold shapes collapse into the warm
# envelope because every in-bounds shape keys onto a precompiled
# bucket); the trend is held loosely (rel_tol 1.0 + abs_tol 0.25) like
# the other CPU-proxy serving walls — a ratio of two shared-machine
# p99s is noisy, and the hard bound is the real gate (check_lattice
# enforces it per record; this table re-states it so a future edit
# cannot silently drop it from history).
LATTICE_SERIES: Tuple[Dict, ...] = (
    {"field": "p99_cold_over_warm", "direction": "lower",
     "rel_tol": 1.0, "abs_tol": 0.25, "ceiling": 2.0, "since": 20,
     "label": "never-seen-shape p99 over warm p99 (lattice admission)"},
)

# ROUTER artifacts (round 21: tools/serve_load.py --router-out)
# carry the fleet-routing headlines: the weak-scaling throughput
# factor of the routed >= 3-replica fleet over one replica (hard
# floor 1.6 — the acceptance bar, re-stated here so a future round
# cannot regress below it silently) and the mid-burst-added replica's
# first routed request over the fleet warm p99 (hard ceiling 2.0 —
# the shared-warm-tier proof; a cold-started replica pays seconds of
# XLA compile and blows the ceiling by orders of magnitude).  Both
# trends are held loosely (rel_tol 1.0) like the other shared-box
# serving walls; the bounds are the real gates and check_router
# enforces them per record.
ROUTER_SERIES: Tuple[Dict, ...] = (
    {"field": "scaling_factor", "direction": "higher",
     "rel_tol": 1.0, "floor": 1.6, "since": 21,
     "label": "fleet throughput scaling over one replica "
              "(weak-scaling protocol)"},
    {"field": "warm_p99_ratio", "direction": "lower",
     "rel_tol": 1.0, "abs_tol": 0.5, "ceiling": 2.0, "since": 21,
     "label": "mid-burst-added replica first request over fleet "
              "warm p99 (shared warm tier)"},
)

# TRACE artifacts (round 22: tools/serve_load.py --trace-out) carry
# the fleet-trace-fabric headlines: how much of the router-observed
# wall the joined cross-process waterfall attributes to NAMED spans
# (hard floor 0.95 — the acceptance bar; below it the join is leaving
# real work invisible), and the router tracing overhead measured
# min-paired-delta between a traced and an untraced router (hard
# ceiling 0.02 — the same telemetry budget the sentinel watches via
# `ia_route_trace_overhead_frac`).  Both trends are held loosely
# (rel_tol 1.0; overhead also abs_tol 0.01 because min-paired-delta
# clamps to 0.0 when the paired arms tie, and a literal-zero best
# would make ANY later positive measurement a "regression"); the
# hard bounds are the real gates and check_fleet_trace enforces them
# per record — this table tracks the trend AND re-states the bounds
# so a future checker edit cannot silently drop them from history.
TRACE_SERIES: Tuple[Dict, ...] = (
    {"field": "critical_path_coverage", "direction": "higher",
     "rel_tol": 1.0, "floor": 0.95, "since": 22,
     "label": "fleet waterfall critical-path coverage "
              "(attributed/total over the router-observed wall)"},
    {"field": "router_trace_overhead_frac", "direction": "lower",
     "rel_tol": 1.0, "abs_tol": 0.01, "ceiling": 0.02, "since": 22,
     "label": "router trace-fabric overhead fraction "
              "(min-paired-delta, traced vs bare router)"},
)

# ARCHIVE artifacts (round 23: tools/archive_drill.py) carry the
# durable-telemetry headlines: baseline continuity and incident-bundle
# completeness are ABSOLUTE invariants (floor 1.0 — a restart that
# forgets its baselines, or a black box missing a section, is a
# regression no trend tolerance excuses), and the archive write-path
# overhead fraction rides the same 2% telemetry budget as the
# observatory/trace surfaces (loose trend — rel_tol 1.0 + abs_tol
# 0.01, because the self-measured fraction on a quiet drill daemon is
# near-zero and noisy — with the hard ceiling as the real gate,
# re-stated here so a future check_archive edit cannot silently drop
# it from history).
ARCHIVE_SERIES: Tuple[Dict, ...] = (
    {"field": "baseline_continuity", "direction": "higher",
     "abs_tol": 0.0, "floor": 1.0, "since": 23,
     "label": "restart baseline/generation continuity (1.0 = the "
              "restarted daemon grades against pre-restart state)"},
    {"field": "capture_completeness", "direction": "higher",
     "abs_tol": 0.0, "floor": 1.0, "since": 23,
     "label": "incident-bundle completeness (1.0 = every required "
              "section present and renderable)"},
    {"field": "archive_overhead_frac", "direction": "lower",
     "rel_tol": 1.0, "abs_tol": 0.01, "ceiling": 0.02, "since": 23,
     "label": "archive write-path overhead fraction (live "
              "ia_archive_overhead_frac gauge, worst drilled boot)"},
)

# SCALE rows are keyed by size; each series is tracked per size.
SCALE_SERIES: Tuple[Dict, ...] = (
    {"field": "wall_s", "direction": "lower", "rel_tol": 0.10,
     "since": 3, "label": "scale wall (s)"},
    {"field": "dist_ratio_vs_exact", "direction": "lower",
     "rel_tol": 0.05, "ceiling": 1.80, "since": 4,
     "label": "dist ratio vs exact NN (declared envelope <= 1.80; "
              "r4 accepted the streaming-kernel trade)"},
    {"field": "psnr_vs_full_oracle_db", "direction": "higher",
     "abs_tol": 0.30, "floor": 35.0, "since": 4,
     "label": "PSNR vs full-synthesis oracle (dB)"},
)


def _num(v) -> bool:
    return (
        isinstance(v, (int, float)) and not isinstance(v, bool)
        and math.isfinite(v)
    )


def cell_provenance(container: dict, field: str) -> str:
    """measured | carried | modeled for one cell: the per-field
    `cell_provenance` map wins, then the row/record-level `provenance`
    key; absent means measured (true of all pre-round-9 artifacts)."""
    per_cell = container.get("cell_provenance")
    if isinstance(per_cell, dict) and field in per_cell:
        return per_cell[field]
    return container.get("provenance", "measured")


# Byte/efficiency cells that a COMPRESSED-mode bench record (round 11:
# kernel_cand_dtype != "bf16" or a prune with survival < 1) reports
# under a different byte model than the uncompressed series tracks.
# They register as modeled — schema-validated and listed, but they
# never set a measured bar and never count as wins: a compressed run's
# smaller bytes/sweep must not become the floor an uncompressed
# measurement is judged against (nor, until the hardware A/B flips the
# default, a claimed improvement).
_COMPRESSED_MODEL_FIELDS = (
    "kernel_bytes_per_sweep",
    "kernel_bytes_per_sweep_useful",
    "kernel_candidate_dma_efficiency",
    "kernel_bytes_per_polish",
    "kernel_bytes_per_polish_useful",
    "kernel_polish_dma_efficiency",
    "kernel_hbm_roofline_frac",
    "kernel_sweep_ms",
)


def _mark_compressed_cells(rec):
    """Force the byte-model cells of a compressed-mode bench record to
    `cell_provenance: modeled` (explicit row/cell provenance wins —
    setdefault only)."""
    if not isinstance(rec, dict):
        return rec
    dt = rec.get("kernel_cand_dtype")
    surv = rec.get("kernel_prune_survival")
    compressed = (dt is not None and dt != "bf16") or (
        isinstance(surv, (int, float)) and not isinstance(surv, bool)
        and surv < 1
    )
    if not compressed:
        return rec
    cp = dict(rec.get("cell_provenance") or {})
    for field in _COMPRESSED_MODEL_FIELDS:
        cp.setdefault(field, "modeled")
    return {**rec, "cell_provenance": cp}


# -------------------------------------------------------------- loading
def _flatten_video(rec):
    """Tracked VIDEO cells, hoisted out of the record's nested sections
    so `check_series` sees the flat {field: value} shape the other
    artifact kinds provide.  Record-level provenance and any per-cell
    map pass through under the same keys."""
    if not isinstance(rec, dict):
        return rec
    flat = {}
    if "provenance" in rec:
        flat["provenance"] = rec["provenance"]
    if isinstance(rec.get("cell_provenance"), dict):
        flat["cell_provenance"] = rec["cell_provenance"]
    flick = rec.get("flicker")
    if isinstance(flick, dict):
        flat["flicker_warm_tau"] = flick.get("warm_tau")
    warm = rec.get("warm")
    if isinstance(warm, dict):
        flat["warm_cost_ratio"] = warm.get("warm_cost_ratio")
    qual = rec.get("quality")
    if isinstance(qual, dict):
        flat["quality_mean_delta_db"] = qual.get("mean_delta_db")
    return flat


def _flatten_serve_persist(rec):
    """Tracked SERVE_PERSIST cells hoisted out of the nested record,
    same shape discipline as `_flatten_video`."""
    if not isinstance(rec, dict):
        return rec
    flat = {}
    if "provenance" in rec:
        flat["provenance"] = rec["provenance"]
    if isinstance(rec.get("cell_provenance"), dict):
        flat["cell_provenance"] = rec["cell_provenance"]
    persist = rec.get("persist")
    if isinstance(persist, dict):
        flat["cold_restart_ms"] = persist.get("cold_restart_ms")
    pipe = rec.get("pipeline")
    if isinstance(pipe, dict):
        flat["p99_warm_ms"] = pipe.get("p99_warm_ms")
    return flat


def load_history(root: str):
    """(bench, scale, video, slo, chaos_serve, mesh2d, serve_persist,
    obs, lattice, router, trace, archive) lists of
    (round, filename, payload), round-sorted.  BENCH payloads unwrap the driver's capture wrapper
    to the parsed record.  Builder probe files (BENCH_r*_builder*.json)
    do not match the round pattern and are deliberately out of scope —
    they are CPU-built field-builder exercises, not round records.
    Compressed-mode records get their byte-model cells forced to
    modeled (`_mark_compressed_cells`); VIDEO payloads stay raw here
    (schema validation needs the nested record) and are flattened at
    the series check."""
    bench, scale, video, slo, chaos_serve, mesh2d = (
        [], [], [], [], [], []
    )
    serve_persist = []
    obs = []
    lattice = []
    router = []
    trace = []
    archive = []
    for name in sorted(os.listdir(root)):
        m = _BENCH_RE.match(name)
        if m:
            with open(os.path.join(root, name)) as f:
                data = json.load(f)
            # A non-object top level (truncated/hand-edited artifact)
            # must surface as a schema violation downstream, not an
            # AttributeError here.
            rec = data
            if isinstance(data, dict) and isinstance(
                data.get("parsed"), dict
            ):
                rec = data["parsed"]
            bench.append((int(m.group(1)), name, _mark_compressed_cells(rec)))
        m = _SCALE_RE.match(name)
        if m:
            with open(os.path.join(root, name)) as f:
                scale.append((int(m.group(1)), name, json.load(f)))
        m = _VIDEO_RE.match(name)
        if m:
            with open(os.path.join(root, name)) as f:
                video.append((int(m.group(1)), name, json.load(f)))
        m = _SLO_RE.match(name)
        if m:
            with open(os.path.join(root, name)) as f:
                slo.append((int(m.group(1)), name, json.load(f)))
        m = _CHAOS_SERVE_RE.match(name)
        if m:
            with open(os.path.join(root, name)) as f:
                chaos_serve.append((int(m.group(1)), name, json.load(f)))
        m = _MESH2D_RE.match(name)
        if m:
            with open(os.path.join(root, name)) as f:
                mesh2d.append((int(m.group(1)), name, json.load(f)))
        m = _SERVE_PERSIST_RE.match(name)
        if m:
            with open(os.path.join(root, name)) as f:
                data = json.load(f)
            # SERVE_r13.json (kind "serve", the round-13 load sweep)
            # shares the filename pattern; only serve_persist records
            # enter this history.
            if isinstance(data, dict) and \
                    data.get("kind") == "serve_persist":
                serve_persist.append((int(m.group(1)), name, data))
        m = _OBS_RE.match(name)
        if m:
            with open(os.path.join(root, name)) as f:
                obs.append((int(m.group(1)), name, json.load(f)))
        m = _LATTICE_RE.match(name)
        if m:
            with open(os.path.join(root, name)) as f:
                lattice.append((int(m.group(1)), name, json.load(f)))
        m = _ROUTER_RE.match(name)
        if m:
            with open(os.path.join(root, name)) as f:
                router.append((int(m.group(1)), name, json.load(f)))
        m = _TRACE_RE.match(name)
        if m:
            with open(os.path.join(root, name)) as f:
                trace.append((int(m.group(1)), name, json.load(f)))
        m = _ARCHIVE_RE.match(name)
        if m:
            with open(os.path.join(root, name)) as f:
                archive.append((int(m.group(1)), name, json.load(f)))
    bench.sort(key=lambda t: t[0])
    scale.sort(key=lambda t: t[0])
    video.sort(key=lambda t: t[0])
    slo.sort(key=lambda t: t[0])
    chaos_serve.sort(key=lambda t: t[0])
    mesh2d.sort(key=lambda t: t[0])
    serve_persist.sort(key=lambda t: t[0])
    obs.sort(key=lambda t: t[0])
    lattice.sort(key=lambda t: t[0])
    router.sort(key=lambda t: t[0])
    trace.sort(key=lambda t: t[0])
    archive.sort(key=lambda t: t[0])
    return (bench, scale, video, slo, chaos_serve, mesh2d,
            serve_persist, obs, lattice, router, trace, archive)


# ------------------------------------------------------ schema (by era)
def validate_bench_record(rnd: int, name: str, rec: dict) -> List[str]:
    errs: List[str] = []
    if not isinstance(rec, dict):
        return [f"{name}: record is not a JSON object"]
    if rnd >= 9:
        # Current era: the full tools/check_bench.py contract,
        # including the enforced instrument ranking and the embedded
        # health verdict every bench.py record now ships.
        tools_dir = os.path.dirname(os.path.abspath(__file__))
        if tools_dir not in sys.path:
            sys.path.insert(0, tools_dir)
        from check_bench import validate_bench

        errs.extend(f"{name}: {e}" for e in validate_bench(rec))
        if "health" not in rec:
            errs.append(
                f"{name}: round-{rnd} record missing its embedded "
                "run-sentinel 'health' verdict"
            )
        return errs
    # Headline questions, every era.
    if not isinstance(rec.get("metric"), str):
        errs.append(f"{name}: metric missing or not a string")
    if not (_num(rec.get("value")) and rec.get("value", 0) > 0):
        errs.append(f"{name}: value {rec.get('value')!r} not positive")
    if rec.get("unit") != "s":
        errs.append(f"{name}: unit {rec.get('unit')!r} != 's'")
    if rec.get("device") not in ("tpu", "cpu-fallback"):
        errs.append(f"{name}: device {rec.get('device')!r} unknown")
    if not _num(rec.get("psnr_vs_cpu_ref_db")):
        errs.append(f"{name}: psnr_vs_cpu_ref_db missing")
    if rnd >= 3:
        configs = rec.get("acceptance_configs")
        if not isinstance(configs, list) or not configs:
            errs.append(f"{name}: acceptance_configs missing or empty")
        else:
            for i, row in enumerate(configs):
                if not isinstance(row, dict) or not (
                    _num(row.get("wall_s")) and row["wall_s"] > 0
                ):
                    errs.append(
                        f"{name}: acceptance_configs[{i}] lacks a "
                        "positive wall_s"
                    )
    for key in ("kernel_hbm_roofline_frac", "kernel_vpu_roofline_frac",
                "kernel_mxu_roofline_frac"):
        frac = rec.get(key)
        if frac is not None and (
            not _num(frac) or frac < 0 or frac > 1.0
        ):
            errs.append(
                f"{name}: {key}={frac!r} outside [0, 1] — impossible"
            )
    return errs


def validate_scale_artifact(rnd: int, name: str, data: dict) -> List[str]:
    errs: List[str] = []
    if not isinstance(data, dict):
        return [f"{name}: artifact is not a JSON object"]
    if not isinstance(data.get("comment"), str) or not data["comment"]:
        errs.append(f"{name}: missing provenance comment")
    rows = data.get("rows")
    if not isinstance(rows, list) or not rows:
        return errs + [f"{name}: rows missing or empty"]
    last_size = 0
    for i, row in enumerate(rows):
        if not isinstance(row, dict):
            errs.append(f"{name}: rows[{i}] is not an object")
            continue
        size = row.get("size")
        if not (_num(size) and size > 0):
            errs.append(f"{name}: rows[{i}] size {size!r} not positive")
            continue
        if size <= last_size:
            errs.append(
                f"{name}: rows[{i}] size {size} not strictly increasing"
            )
        last_size = size
        if cell_provenance(row, "wall_s") == "measured" and not (
            _num(row.get("wall_s")) and row["wall_s"] > 0
        ):
            errs.append(
                f"{name}: rows[{i}] (size {size}) wall_s "
                f"{row.get('wall_s')!r} not positive"
            )
        lvl = row.get("level_wall_ms")
        if lvl is not None and (
            not isinstance(lvl, list)
            or not lvl
            or not all(_num(v) and v > 0 for v in lvl)
        ):
            errs.append(
                f"{name}: rows[{i}] (size {size}) level_wall_ms is not "
                "a list of positive walls"
            )
        e0 = row.get("nnf_energy_level0")
        if e0 is not None and (not _num(e0) or e0 < 0):
            errs.append(
                f"{name}: rows[{i}] (size {size}) nnf_energy_level0 "
                f"{e0!r} not finite/non-negative"
            )
        dr = row.get("dist_ratio_vs_exact")
        if dr is not None and (not _num(dr) or dr < 1.0):
            errs.append(
                f"{name}: rows[{i}] (size {size}) dist_ratio_vs_exact "
                f"{dr!r} below 1.0 — an approximation cannot beat the "
                "exact oracle; the probe is broken"
            )
        prov = row.get("provenance")
        if prov is not None and prov not in PROVENANCES:
            errs.append(
                f"{name}: rows[{i}] provenance {prov!r} names none of "
                f"{PROVENANCES}"
            )
    return errs


# --------------------------------------------------------- trajectories
def _worse_than(value: float, best: float, decl: Dict) -> bool:
    """True when `value` regresses past `best` beyond the declared
    tolerance (either bound passing suffices when both are given)."""
    rel = decl.get("rel_tol")
    abs_ = decl.get("abs_tol")
    if decl["direction"] == "lower":
        bounds = []
        if rel is not None:
            bounds.append(best * (1 + rel))
        if abs_ is not None:
            bounds.append(best + abs_)
        return value > max(bounds)  # regressed past EVERY allowance
    bounds = []
    if rel is not None:
        bounds.append(best * (1 - rel))
    if abs_ is not None:
        bounds.append(best - abs_)
    return value < min(bounds)  # regressed past EVERY allowance


def _bound_violation(value: float, decl: Dict) -> Optional[str]:
    floor = decl.get("floor")
    ceiling = decl.get("ceiling")
    if floor is not None and value < floor:
        return f"below the declared floor {floor}"
    if ceiling is not None and value > ceiling:
        return f"above the declared ceiling {ceiling}"
    return None


def check_series(
    decl: Dict, cells: List[Tuple[int, str, dict]], series_name: str,
    errs: List[str], report: List[Dict],
) -> None:
    """One tracked series over (round, artifact, container) cells:
    measured cells compare against the best prior measured cell and
    then (only they) may advance it; carried/modeled cells are listed
    but inert (module docstring's provenance discipline)."""
    field = decl["field"]
    best: Optional[float] = None
    best_at = None
    n_meas = n_inert = 0
    for rnd, name, container in cells:
        if rnd < decl["since"] or not isinstance(container, dict):
            continue  # non-object containers already failed schema
        value = container.get(field)
        if value is None:
            continue
        prov = cell_provenance(container, field)
        entry = {
            "series": series_name, "round": rnd, "artifact": name,
            "value": value, "provenance": prov, "status": "ok",
        }
        if prov not in PROVENANCES:
            errs.append(
                f"{name}: {series_name} round {rnd}: provenance "
                f"{prov!r} names none of {PROVENANCES}"
            )
            entry["status"] = "invalid"
            report.append(entry)
            continue
        if not _num(value):
            errs.append(
                f"{name}: {series_name} round {rnd}: value {value!r} "
                "is not a finite number"
            )
            entry["status"] = "invalid"
            report.append(entry)
            continue
        if prov != "measured":
            n_inert += 1
            entry["status"] = "inert"
            report.append(entry)
            continue
        n_meas += 1
        bound = _bound_violation(value, decl)
        if bound is not None:
            errs.append(
                f"{name}: {series_name} round {rnd}: {value} {bound}"
            )
            entry["status"] = "violated"
        elif best is not None and _worse_than(value, best, decl):
            errs.append(
                f"{name}: {series_name} round {rnd}: {value} regresses "
                f"past the best prior measured {best} (round "
                f"{best_at[0]}, {best_at[1]}) beyond tolerance "
                f"{{rel={decl.get('rel_tol')}, "
                f"abs={decl.get('abs_tol')}}}"
            )
            entry["status"] = "violated"
        better = best is None or (
            value < best if decl["direction"] == "lower" else value > best
        )
        if better:
            best, best_at = value, (rnd, name)
        report.append(entry)
    if n_meas or n_inert:
        report.append({
            "series": series_name, "summary": True,
            "measured_cells": n_meas, "inert_cells": n_inert,
            "best": best,
            "best_at": best_at[1] if best_at else None,
        })


def check_trajectory(root: str) -> Tuple[List[str], List[Dict]]:
    """All schema + trajectory checks over the committed history.
    Returns (violations, machine-readable report rows)."""
    (bench, scale, video, slo, chaos_serve, mesh2d, serve_persist,
     obs, lattice, router, trace, archive) = load_history(root)
    errs: List[str] = []
    report: List[Dict] = []

    tools_dir = os.path.dirname(os.path.abspath(__file__))
    if tools_dir not in sys.path:
        sys.path.insert(0, tools_dir)
    for rnd, name, rec in bench:
        errs.extend(validate_bench_record(rnd, name, rec))
    for rnd, name, data in scale:
        errs.extend(validate_scale_artifact(rnd, name, data))
    for rnd, name, rec in video:
        # Video artifacts carry their full contract in check_video.
        from check_video import validate_video

        errs.extend(f"{name}: {e}" for e in validate_video(rec))
    for rnd, name, rec in slo:
        # SLO artifacts carry their full contract in check_slo.
        from check_slo import validate_slo

        errs.extend(f"{name}: {e}" for e in validate_slo(rec))
    for rnd, name, rec in chaos_serve:
        # Serving-chaos artifacts carry their full contract in
        # check_chaos_serve.
        from check_chaos_serve import validate_chaos_serve

        errs.extend(f"{name}: {e}" for e in validate_chaos_serve(rec))
    for rnd, name, rec in mesh2d:
        # 2-D mesh artifacts carry their full contract — including the
        # modeled-row re-pricing — in check_mesh2d.
        from check_mesh2d import validate_mesh2d

        errs.extend(f"{name}: {e}" for e in validate_mesh2d(rec))
    for rnd, name, rec in serve_persist:
        # Persistent-cache artifacts carry their full contract —
        # including the 10x restart gate — in check_serve_persist.
        from check_serve_persist import validate_serve_persist

        errs.extend(
            f"{name}: {e}" for e in validate_serve_persist(rec)
        )
    for rnd, name, rec in obs:
        # Observatory artifacts carry their full contract — including
        # the fleet-SLO bit-equality re-derivation — in check_obs.
        from check_obs import validate_obs

        errs.extend(f"{name}: {e}" for e in validate_obs(rec))
    for rnd, name, rec in lattice:
        # Shape-lattice artifacts carry their full contract — bounded
        # keys, all-hit burst, crop bit-identity, honest bypass — in
        # check_lattice.
        from check_lattice import validate_lattice

        errs.extend(f"{name}: {e}" for e in validate_lattice(rec))

    for rnd, name, rec in router:
        # Fleet-routing artifacts carry their full contract — the
        # scaling floor, warm-start ceiling, affinity matrix and the
        # chaos replica-kill gates — in check_router.
        from check_router import validate_router

        errs.extend(f"{name}: {e}" for e in validate_router(rec))

    for rnd, name, rec in trace:
        # Fleet-trace artifacts carry their full contract — the
        # re-derived attribution arithmetic, retry reconciliation,
        # migration spans and the overhead budget — in
        # check_fleet_trace.
        from check_fleet_trace import validate_fleet_trace

        errs.extend(
            f"{name}: {e}" for e in validate_fleet_trace(rec)
        )

    for rnd, name, rec in archive:
        # Durable-telemetry artifacts carry their full contract — the
        # restart-continuity floors, the exactly-one-bundle capture
        # gate, torn-tail tolerance and the overhead ceiling — in
        # check_archive.
        from check_archive import validate_archive

        errs.extend(f"{name}: {e}" for e in validate_archive(rec))

    for decl in BENCH_SERIES:
        check_series(
            decl, [(r, n, rec) for r, n, rec in bench],
            f"bench.{decl['field']}", errs, report,
        )
    for decl in VIDEO_SERIES:
        check_series(
            decl, [(r, n, _flatten_video(rec)) for r, n, rec in video],
            f"video.{decl['field']}", errs, report,
        )
    for decl in SLO_SERIES:
        # SLO headline cells are already top-level — no flattener.
        check_series(
            decl, [(r, n, rec) for r, n, rec in slo],
            f"slo.{decl['field']}", errs, report,
        )
    for decl in CHAOS_SERVE_SERIES:
        # Chaos-serve headline cells are top-level too.
        check_series(
            decl, [(r, n, rec) for r, n, rec in chaos_serve],
            f"chaos_serve.{decl['field']}", errs, report,
        )
    for decl in SERVE_PERSIST_SERIES:
        check_series(
            decl,
            [(r, n, _flatten_serve_persist(rec))
             for r, n, rec in serve_persist],
            f"serve_persist.{decl['field']}", errs, report,
        )
    for decl in OBS_SERIES:
        # The overhead headline is top-level in the OBS record.
        check_series(
            decl, [(r, n, rec) for r, n, rec in obs],
            f"obs.{decl['field']}", errs, report,
        )
    for decl in LATTICE_SERIES:
        # The cold/warm p99 ratio is top-level in the LATTICE record.
        check_series(
            decl, [(r, n, rec) for r, n, rec in lattice],
            f"lattice.{decl['field']}", errs, report,
        )
    for decl in ROUTER_SERIES:
        # scaling_factor is top-level; the warm-start ratio lives
        # under warm_start — flatten the two headline cells.
        check_series(
            decl,
            [(r, n, {
                "scaling_factor": rec.get("scaling_factor"),
                "warm_p99_ratio": (rec.get("warm_start") or {})
                .get("warm_p99_ratio"),
            }) for r, n, rec in router],
            f"router.{decl['field']}", errs, report,
        )
    for decl in TRACE_SERIES:
        # Coverage lives under the gated main arm's joined record;
        # the overhead fraction under overhead — flatten both.
        check_series(
            decl,
            [(r, n, {
                "critical_path_coverage":
                    ((rec.get("main") or {}).get("joined") or {})
                    .get("critical_path_coverage"),
                "router_trace_overhead_frac":
                    (rec.get("overhead") or {}).get("frac"),
            }) for r, n, rec in trace],
            f"trace.{decl['field']}", errs, report,
        )
    for decl in ARCHIVE_SERIES:
        # The durable-telemetry headline cells are top-level.
        check_series(
            decl, [(r, n, rec) for r, n, rec in archive],
            f"archive.{decl['field']}", errs, report,
        )
    def _rows(data):
        rows = data.get("rows") if isinstance(data, dict) else None
        return [r for r in (rows or []) if isinstance(r, dict)]

    sizes = sorted({
        row.get("size")
        for _, _, data in scale
        for row in _rows(data)
        if _num(row.get("size"))
    })
    for decl in SCALE_SERIES:
        for size in sizes:
            cells = [
                (r, n, row)
                for r, n, data in scale
                for row in _rows(data)
                if row.get("size") == size
            ]
            check_series(
                decl, cells, f"scale.{size}.{decl['field']}", errs,
                report,
            )
    mesh2d_sizes = sorted({
        row.get("size")
        for _, _, data in mesh2d
        for row in _rows(data)
        if _num(row.get("size"))
    })
    for decl in MESH2D_SERIES:
        for size in mesh2d_sizes:
            cells = [
                (r, n, row)
                for r, n, data in mesh2d
                for row in _rows(data)
                if row.get("size") == size
            ]
            check_series(
                decl, cells, f"mesh2d.{size}.{decl['field']}", errs,
                report,
            )
    return errs, report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--all", action="store_true",
        help="check every BENCH_r*/SCALE_r* artifact under --root",
    )
    ap.add_argument(
        "--root", default=None,
        help="history directory (default: the repo root this tool "
        "lives in)",
    )
    ap.add_argument(
        "--json", default=None, metavar="OUT",
        help="also write the machine-readable trajectory report here",
    )
    args = ap.parse_args(argv)
    if not args.all:
        ap.error("nothing to do: pass --all")
    root = args.root or os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    )
    try:
        errs, report = check_trajectory(root)
    except (OSError, ValueError) as e:
        print(f"check_trajectory: cannot read history: {e}",
              file=sys.stderr)
        return 2
    if args.json:
        with open(args.json, "w") as f:
            json.dump({"violations": errs, "report": report}, f, indent=1)
            f.write("\n")
    for row in report:
        if row.get("summary"):
            print(
                f"check_trajectory: {row['series']}: "
                f"{row['measured_cells']} measured / "
                f"{row['inert_cells']} carried-or-modeled, best "
                f"{row['best']} ({row['best_at']})"
            )
    if errs:
        for e in errs:
            print(f"check_trajectory: {e}", file=sys.stderr)
        print(
            f"check_trajectory: FAIL — {len(errs)} violation(s)",
            file=sys.stderr,
        )
        return 1
    n_b = len([1 for r in report if not r.get("summary")])
    print(f"check_trajectory: OK — {n_b} tracked cells hold")
    return 0


if __name__ == "__main__":
    sys.exit(main())
