"""Large-image scaling bench: 2048^2 and 4096^2 rows (round-3 VERDICT
task 2: real PSNR at 2048^2, a tighter 4096^2 bound).

Prints one JSON line per size with warm wall, per-level walls, final
NN-field energy, and quality:

- **<= 2048^2: full-synthesis exact-oracle PSNR.**  The brute matcher
  synthesizes B' with exact NN at every level/EM step and the
  patchmatch output is PSNR'd against it — the same metric the 1024^2
  headline uses.  The exact-NN kernel chunks its grid
  (kernels/nn_brute.py _MAX_TILE_ELEMS) and runs at (tq=2048, ta=256)
  tiles here, which cuts the A-table re-streaming 8x vs the default
  tiles (traffic is (N_B/tq) * |A|; tq=2048 is the largest that fits
  the 16 MB scoped-VMEM limit — measured 2026-07-31: tq=3072 and 4096
  both fail AOT compile with scoped-vmem OOM at D=128 bf16).
- **4096^2: stratified exact probe + bootstrap CI.**  A full-synthesis
  oracle at 4096^2 is ~2.4 PFLOP of exact NN per EM step — hours of
  wall for one row — so quality is bounded by a 1M-pixel STRATIFIED
  sample (one jittered pixel per 16-pixel stratum of the flat index
  space) of the final level-0 field, exact-searched against the FULL A
  database, reporting the achieved/exact mean-distance ratio with a
  bootstrap 95% CI, plus the exact-match fraction.  The 1024^2 and
  2048^2 rows carry the same probe alongside their full-oracle PSNR,
  calibrating the ratio against known PSNR.

Run on the TPU box:  python tools/scale_bench.py [max_size]
                     python tools/scale_bench.py --sizes 3072 ...
(the --sizes form runs an explicit list, e.g. the off-grid 3072 row)

**2-D bands x slabs mode (round 17):**

    JAX_PLATFORMS=cpu python tools/scale_bench.py --mesh2d \
        [--out MESH2D_r17.json] [--sizes N ...]

Runs the spatial runner on the planner-chosen (bands, slabs) mesh at
each measured size — warm walls, bit-identity against the 1-D runner
at the same slab count, the joint 2-D collective schedule — then
appends the 8192^2 / 16384^2 / 32768^2 scale rows this box cannot
measure as provenance-"modeled" cells priced by the SAME analytic
models the sentinel pins (parallel/plan2d.py score + comms.py
schedule + the candidate-DMA byte model) against stated v5e
bandwidths.  tools/check_mesh2d.py recomputes every modeled cell from
its recorded inputs, so a hand-edited projection fails tier-1; the
hardware verdict (and its pre-stated wall-only kill criterion) lives
in tools/mesh2d_ab.py.
"""

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# The 2-D mode wants a factorizable device count; on a CPU-only box
# expose the same 8-virtual-device topology the 2-D tests pin.  Must
# happen before jax imports.
if "--mesh2d" in sys.argv and os.environ.get("JAX_PLATFORMS") == "cpu" \
        and "host_platform_device_count" not in os.environ.get(
            "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np
import jax
import jax.numpy as jnp

from image_analogies_tpu.utils.cache import enable_compilation_cache

enable_compilation_cache()

from image_analogies_tpu import SynthConfig, create_image_analogy, psnr
from image_analogies_tpu.utils.examples import super_resolution
from image_analogies_tpu.utils.progress import ProgressWriter
from image_analogies_tpu.utils.kernelbench import sync as _sync

_N_PROBE = 1 << 20
# Full-synthesis oracle ceiling: the exact-NN work is quadratic in
# pixels (2048^2 is ~0.6 PFLOP/EM step at bf16 match precision; 4096^2
# is ~16x that), so the full oracle runs up to 2048^2 and the 4096^2
# row is bounded by the stratified probe.
_FULL_ORACLE_MAX = 2048
_NN_TILES = dict(tq=2048, ta=256)


def _stratified_probe_idx(n_px: int, n_probe: int, rng) -> np.ndarray:
    """One jittered sample per stratum of the flat index space."""
    stride = n_px // n_probe
    base = np.arange(n_probe, dtype=np.int64) * stride
    return (base + rng.integers(0, stride, n_probe)).astype(np.int32)


def _exact_probe(a, ap, b, cfg, aux):
    """(mean achieved dist / mean exact dist with bootstrap 95% CI,
    exact-match fraction) on _N_PROBE stratified pixels of the final
    level-0 field, measured at the EM fixed point: features are rebuilt
    from the run's own final estimates (B'_l = gather(A'_l, nnf_l) —
    per-level estimates are fully determined by the aux fields), both
    sides in the lean bf16 feature space so achieved and exact
    distances share one metric."""
    from image_analogies_tpu.kernels.nn_brute import exact_nn_pallas
    from image_analogies_tpu.models.analogy import (
        _prologue_fn,
        assemble_features_lean,
    )

    levels = cfg.clamp_levels(a.shape[:2], b.shape[:2])
    (
        pyr_src_a, pyr_flt_a, pyr_src_b, pyr_copy_a, _pyr_raw_b, _yiq
    ) = _prologue_fn(cfg, levels)(a, ap, b)

    def planes(lvl):
        nnf = aux["nnf"][lvl]
        if isinstance(nnf, tuple):
            return nnf
        return nnf[..., 0], nnf[..., 1]

    def estimate(lvl):
        py, px = planes(lvl)
        copy_a = pyr_copy_a[lvl]
        ha_l, wa_l = copy_a.shape[:2]
        flat = copy_a.reshape(ha_l * wa_l, -1)
        out = jnp.take(flat, (py * wa_l + px).reshape(-1), axis=0)
        out = out.reshape(*py.shape, -1)
        return out[..., 0] if copy_a.ndim == 2 else out

    py0, px0 = planes(0)
    h, w = py0.shape
    ha, wa = pyr_src_a[0].shape[:2]
    flt0 = estimate(0)
    flt1 = estimate(1)

    f_b_tab = assemble_features_lean(
        pyr_src_b[0], flt0, cfg, pyr_src_b[1], flt1
    )
    f_a_tab = assemble_features_lean(
        pyr_src_a[0], pyr_flt_a[0], cfg, pyr_src_a[1], pyr_flt_a[1]
    )

    rng = np.random.default_rng(0)
    n_probe = min(_N_PROBE, h * w // 2)
    probe = jnp.asarray(_stratified_probe_idx(h * w, n_probe, rng))
    fb_rows = jnp.take(f_b_tab, probe, axis=0).astype(jnp.float32)
    idx_ach = jnp.take((py0 * wa + px0).reshape(-1), probe, axis=0)
    # Only the gathered probe rows are needed from the B side; the full
    # table is 4.3 GB at 4096^2 and the exact search wants that HBM.
    del f_b_tab, flt0, flt1

    idx_exact, d_exact = exact_nn_pallas(
        fb_rows, f_a_tab, match_dtype=jnp.bfloat16, **_NN_TILES
    )
    rows = jnp.take(f_a_tab, idx_ach, axis=0).astype(jnp.float32)
    d_ach = jnp.sum((fb_rows - rows) ** 2, axis=-1)

    d_ach_np = np.asarray(d_ach, np.float64)
    d_exact_np = np.asarray(d_exact, np.float64)
    ratio = float(d_ach_np.mean()) / max(float(d_exact_np.mean()), 1e-30)
    # Bootstrap 95% CI on the ratio (resample pixels with replacement).
    boots = []
    for _ in range(1000):
        pick = rng.integers(0, n_probe, n_probe)
        boots.append(
            d_ach_np[pick].mean() / max(d_exact_np[pick].mean(), 1e-30)
        )
    lo, hi = np.percentile(boots, [2.5, 97.5])
    match = float(np.mean(np.asarray(idx_ach) == np.asarray(idx_exact)))
    return {
        "exact_probe_pixels": n_probe,
        "probe_sampling": "stratified-jittered",
        "dist_ratio_vs_exact": round(ratio, 4),
        "dist_ratio_ci95": [round(float(lo), 4), round(float(hi), 4)],
        "exact_match_frac": round(match, 4),
    }


# ---------------------------------------------------------------- mesh2d
MESH2D_SCHEMA_VERSION = 1
# Modeled-row pricing constants: v5e-8 class box.  Stated IN the
# artifact (model_bandwidths) so the validator can re-price the cell
# and a reader knows exactly what the projection assumes.
_MESH2D_HBM_BPS = 819e9      # per-chip HBM stream bandwidth
_MESH2D_ICI_BPS = 45e9       # per-link ICI bandwidth, one direction
_MESH2D_HBM_BYTES = 16 * (1 << 30)   # per-chip HBM capacity
_MESH2D_MODELED_SIZES = (8192, 16384, 32768)
# Modeled-row schedule: the committed SCALE rows' search schedule.
_MESH2D_MODEL_CFG = dict(
    levels=6, matcher="patchmatch", em_iters=2, pm_iters=6,
)
# Measured-row schedule: one lean level, short EM — what a CPU box
# (interpret-mode kernel) finishes in minutes; on real chips the same
# row is re-measured compiled.
_MESH2D_MEASURED_CFG = dict(
    levels=1, matcher="patchmatch", em_iters=2, pm_iters=2,
)


def _mesh2d_sync(x):
    jax.block_until_ready(x)
    return x


def mesh2d_modeled_row(size: int, n_devices: int) -> dict:
    """One provenance-"modeled" scale row: planner verdict under the
    stated HBM capacity, cell values priced by the score models, wall
    priced against the stated bandwidths.  No measurement anywhere —
    tools/check_mesh2d.py recomputes every field from model_inputs."""
    from image_analogies_tpu import SynthConfig
    from image_analogies_tpu.parallel.plan2d import plan_mesh_shape

    cfg = SynthConfig(**_MESH2D_MODEL_CFG)
    plan = plan_mesh_shape(
        n_devices, (size, size), (size, size), cfg,
        hbm_bytes=_MESH2D_HBM_BYTES,
    )
    c = plan.chosen
    wall = (
        c.dma_bytes / _MESH2D_HBM_BPS + c.comms_bytes / _MESH2D_ICI_BPS
    )
    return {
        "size": size,
        "provenance": "modeled",
        "mesh_shape": [plan.n_bands, plan.n_slabs],
        "plan": plan.as_attrs(),
        "comms_bytes": c.comms_bytes,
        "dma_bytes": c.dma_bytes,
        "residency_bytes": c.residency_bytes,
        "wall_s": round(wall, 3),
        "model_inputs": {
            "n_devices": n_devices,
            "a_shape": [size, size],
            "b_shape": [size, size],
            "cfg": dict(_MESH2D_MODEL_CFG),
            "hbm_bytes": _MESH2D_HBM_BYTES,
        },
        "model_bandwidths": {
            "hbm_Bps": _MESH2D_HBM_BPS,
            "ici_Bps": _MESH2D_ICI_BPS,
        },
        "basis": (
            "plan2d score (comms schedule + candidate-DMA bytes, "
            "de-leaned levels at the standard-path penalty) priced "
            "against the stated v5e bandwidths; zero measurement — "
            "see tools/mesh2d_ab.py for the hardware verdict recipe "
            "and its pre-stated wall-only kill criterion"
        ),
    }


def mesh2d_measured_row(size: int, n_devices: int) -> dict:
    """One measured 2-D row: run the planner-chosen (bands, slabs)
    mesh, record warm walls, and pin bit-identity against the 1-D
    runner at the SAME slab count (same numerics contract the tests
    pin; the extra bands devices are the thing being bought)."""
    from image_analogies_tpu import SynthConfig
    from image_analogies_tpu.parallel.comms import (
        banded_spatial_level_collectives,
    )
    from image_analogies_tpu.parallel.mesh import make_mesh
    from image_analogies_tpu.parallel.plan2d import plan_mesh_shape
    from image_analogies_tpu.parallel.spatial import synthesize_spatial

    platform = jax.devices()[0].platform
    kw = dict(_MESH2D_MEASURED_CFG)
    if platform == "cpu":
        kw["pallas_mode"] = "interpret"
    cfg = SynthConfig(**kw)
    a, ap, b = super_resolution(size)
    a, ap, b = (jnp.asarray(x, jnp.float32) for x in (a, ap, b))
    plan = plan_mesh_shape(n_devices, a.shape[:2], b.shape[:2], cfg)
    mesh2d = make_mesh(
        plan.n_bands * plan.n_slabs,
        axis_names=("bands", "slabs"),
        shape=(plan.n_bands, plan.n_slabs),
    )

    def run(mesh):
        return np.asarray(_mesh2d_sync(
            synthesize_spatial(a, ap, b, cfg, mesh,
                               mesh_plan=plan.as_attrs())
        ))

    out_2d = run(mesh2d)          # compile
    walls = []
    for _ in range(2):
        t0 = time.perf_counter()
        out_2d = run(mesh2d)
        walls.append(round(time.perf_counter() - t0, 2))

    mesh1d = make_mesh(plan.n_slabs)
    out_1d = run(mesh1d)          # compile
    t0 = time.perf_counter()
    out_1d = run(mesh1d)
    wall_1d = round(time.perf_counter() - t0, 2)

    grain = plan.n_slabs * 2 ** (cfg.clamp_levels(
        a.shape[:2], b.shape[:2]) - 1) * 2
    h_pad = b.shape[0] + ((-b.shape[0]) % grain)
    return {
        "size": size,
        "provenance": "measured",
        "platform": platform,
        "pallas_mode": cfg.pallas_mode,
        "mesh_shape": [plan.n_bands, plan.n_slabs],
        "plan": plan.as_attrs(),
        "wall_s": min(walls),
        "wall_runs_s": walls,
        "wall_1d_same_slabs_s": wall_1d,
        "bit_identical_to_1d": bool(np.array_equal(out_2d, out_1d)),
        "comms_schedule": banded_spatial_level_collectives(
            cfg, a.shape[0], a.shape[1], h_pad, b.shape[1],
            (plan.n_bands, plan.n_slabs),
        ),
    }


def mesh2d_main(argv):
    out_path = None
    sizes = ()
    it = iter(argv)
    for tok in it:
        if tok == "--out":
            out_path = next(it)
        elif tok == "--sizes":
            sizes = sizes + (int(next(it)),)
        elif tok != "--mesh2d":
            raise SystemExit(f"mesh2d: unknown arg {tok!r}")
    if not sizes:
        # What the box allows: 512^2 is the smallest B whose 4-slab
        # cores sit on the kernel's LANE floor, so it is the smallest
        # size where the 2-D mesh is real (bands engage on a lean
        # level) — and the largest an interpret-mode CPU run finishes
        # in minutes.  Real chips pass --sizes to extend.
        sizes = (512,)
    n_dev = len(jax.devices())
    platform = jax.devices()[0].platform
    rows = [mesh2d_measured_row(s, n_dev) for s in sorted(sizes)]
    rows += [
        mesh2d_modeled_row(s, n_dev)
        for s in _MESH2D_MODELED_SIZES
        if s > max(sizes)
    ]
    record = {
        "schema_version": MESH2D_SCHEMA_VERSION,
        "comment": (
            "2-D bands x slabs scale rows (round 17). Measured rows "
            f"ran on this box ({platform}, {n_dev} devices"
            + (", interpret-mode kernel — walls are a CPU proxy, the "
               "tracked series holds them loosely"
               if platform == "cpu" else "")
            + "); modeled rows are priced projections (see each row's "
            "basis), never measurements, and never set a trajectory "
            "bar. Validator: tools/check_mesh2d.py; hardware A/B with "
            "the pre-stated wall-only kill criterion: "
            "tools/mesh2d_ab.py."
        ),
        "n_devices": n_dev,
        "platform": platform,
        "generated_by": "tools/scale_bench.py --mesh2d",
        "rows": rows,
    }
    text = json.dumps(record, indent=1)
    if out_path:
        with open(out_path, "w") as f:
            f.write(text + "\n")
    print(text, flush=True)


def main():
    # `scale_bench.py [max_size]` runs the standard rows up to max_size
    # (the recorded-artifact contract); `scale_bench.py --sizes N...`
    # runs an explicit list (e.g. --sizes 3072 for the off-grid row);
    # `scale_bench.py --mesh2d` runs the 2-D bands x slabs rows.
    if "--mesh2d" in sys.argv[1:]:
        mesh2d_main(sys.argv[1:])
        return
    if sys.argv[1:] and sys.argv[1] == "--sizes":
        if len(sys.argv) < 3:
            raise SystemExit("usage: scale_bench.py --sizes N [N...]")
        sizes = tuple(int(x) for x in sys.argv[2:])
    else:
        max_size = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
        sizes = tuple(s for s in (1024, 2048, 4096) if s <= max_size)
    from unittest import mock

    import image_analogies_tpu.kernels.nn_brute as nb

    for size in sizes:
        a, ap, b = super_resolution(size)
        a, ap, b = (jnp.asarray(x, jnp.float32) for x in (a, ap, b))
        for x in (a, ap, b):
            _sync(x)
        cfg = SynthConfig(
            levels=6 if size > 1024 else 5, matcher="patchmatch",
            em_iters=2, pm_iters=6,
        )
        _sync(create_image_analogy(a, ap, b, cfg))  # compile
        walls = []
        for _ in range(2):
            t0 = time.perf_counter()
            out = create_image_analogy(a, ap, b, cfg)
            _sync(out)
            walls.append(round(time.perf_counter() - t0, 2))

        fd, path = tempfile.mkstemp(suffix=".jsonl")
        os.close(fd)
        level_ms, energy = [], None
        try:
            # One instrumented run yields both the per-level walls AND
            # the aux fields the probe needs (same run, not merely the
            # same seed).
            aux = create_image_analogy(
                a, ap, b, cfg, return_aux=True,
                progress=ProgressWriter(path),
            )
            _sync(aux["bp"])
            for line in open(path):
                rec = json.loads(line)
                if rec.get("event") == "level_done":
                    level_ms.append(rec["wall_ms"])
                    if rec["level"] == 0:
                        energy = rec["nnf_energy"]
        finally:
            os.unlink(path)

        row = {
            "size": size,
            "wall_s": min(walls),
            "wall_runs_s": walls,
            "level_wall_ms": level_ms,
            "nnf_energy_level0": energy,
        }
        row.update(_exact_probe(a, ap, b, cfg, aux))
        # The oracle run needs every byte of HBM at 2048^2 (two 2.1 GB
        # f32 tables + eager temps); drop the instrumented run's aux
        # fields before it starts.
        del aux
        import gc

        gc.collect()

        if size <= _FULL_ORACLE_MAX:
            # Full-synthesis exact-oracle PSNR, with the exact-NN kernel
            # forced onto giant-A tiles.  Crash-safety is structural
            # now: the driver runs oversized brute levels unfused
            # (analogy._SAFE_EXEC_DIST_ELEMS) and exact_nn_pallas
            # chunks its query axis into separate executions
            # (nn_brute._MAX_TILE_ELEMS), so no single device
            # execution outlives the worker's tolerance.
            orig = nb.exact_nn_pallas

            def big_tiles(fb, fa, **kw):
                kw.setdefault("tq", _NN_TILES["tq"])
                kw.setdefault("ta", _NN_TILES["ta"])
                return orig(fb, fa, **kw)

            t0 = time.perf_counter()
            with mock.patch.object(nb, "exact_nn_pallas", big_tiles):
                oracle = create_image_analogy(
                    a, ap, b,
                    SynthConfig(
                        levels=cfg.levels, matcher="brute",
                        em_iters=cfg.em_iters,
                    ),
                )
                _sync(oracle)
            row["oracle_wall_s"] = round(time.perf_counter() - t0, 2)
            row["psnr_vs_full_oracle_db"] = round(
                psnr(np.asarray(out), np.asarray(oracle)), 2
            )

        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
