"""Large-image scaling bench: 2048^2 and 4096^2 lean-path rows (round-2
VERDICT task 5: the large-scale numbers must live in an artifact, not
prose).

Prints one JSON line per size with warm wall, per-level walls, final
NN-field energy, and an EXACT-NN PROBE quality metric: M=128K query
pixels of the final level-0 feature field are exact-searched against
the full A database with the streaming brute kernel, and the run's
achieved distances are compared against the exact optima on those
pixels (mean-distance ratio; 1.0 = the field is exactly optimal on the
probe).  A full-synthesis exact oracle is NOT run at these sizes: the
2048^2 all-pairs pass is a ~134M-step kernel grid that reproducibly
crashes the TPU worker (two attempts, 2026-07-30), while the probe's
few-million-step grid is the same regime the 1024^2 oracle uses safely.

Run on the TPU box:  python tools/scale_bench.py [max_size]
"""

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax
import jax.numpy as jnp

from image_analogies_tpu.utils.cache import enable_compilation_cache

enable_compilation_cache()

from image_analogies_tpu import SynthConfig, create_image_analogy
from image_analogies_tpu.utils.examples import super_resolution
from image_analogies_tpu.utils.progress import ProgressWriter
from image_analogies_tpu.utils.kernelbench import sync as _sync

_N_PROBE = 1 << 17


def _exact_probe(a, ap, b, cfg, aux):
    """(mean achieved dist / mean exact dist, exact-match fraction) on
    _N_PROBE random pixels of the final level-0 field, measured at the
    EM fixed point: features are rebuilt from the run's own final
    estimates (B'_l = gather(A'_l, nnf_l) — per-level estimates are
    fully determined by the aux fields), both sides in the lean bf16
    feature space so achieved and exact distances share one metric."""
    from image_analogies_tpu.kernels.nn_brute import exact_nn_pallas
    from image_analogies_tpu.models.analogy import (
        _prologue_fn,
        assemble_features_lean,
    )

    levels = cfg.clamp_levels(a.shape[:2], b.shape[:2])
    (
        pyr_src_a, pyr_flt_a, pyr_src_b, pyr_copy_a, _pyr_raw_b, _yiq
    ) = _prologue_fn(cfg, levels)(a, ap, b)

    def planes(lvl):
        nnf = aux["nnf"][lvl]
        if isinstance(nnf, tuple):
            return nnf
        return nnf[..., 0], nnf[..., 1]

    def estimate(lvl):
        py, px = planes(lvl)
        copy_a = pyr_copy_a[lvl]
        ha_l, wa_l = copy_a.shape[:2]
        flat = copy_a.reshape(ha_l * wa_l, -1)
        out = jnp.take(flat, (py * wa_l + px).reshape(-1), axis=0)
        out = out.reshape(*py.shape, -1)
        return out[..., 0] if copy_a.ndim == 2 else out

    py0, px0 = planes(0)
    h, w = py0.shape
    ha, wa = pyr_src_a[0].shape[:2]
    flt0 = estimate(0)
    flt1 = estimate(1)

    f_b_tab = assemble_features_lean(
        pyr_src_b[0], flt0, cfg, pyr_src_b[1], flt1
    )
    f_a_tab = assemble_features_lean(
        pyr_src_a[0], pyr_flt_a[0], cfg, pyr_src_a[1], pyr_flt_a[1]
    )

    rng = np.random.default_rng(0)
    probe = jnp.asarray(
        rng.choice(h * w, size=_N_PROBE, replace=False).astype(np.int32)
    )
    fb_rows = jnp.take(f_b_tab, probe, axis=0).astype(jnp.float32)
    idx_ach = jnp.take((py0 * wa + px0).reshape(-1), probe, axis=0)

    idx_exact, d_exact = exact_nn_pallas(
        fb_rows, f_a_tab, match_dtype=jnp.bfloat16
    )
    rows = jnp.take(f_a_tab, idx_ach, axis=0).astype(jnp.float32)
    d_ach = jnp.sum((fb_rows - rows) ** 2, axis=-1)
    ratio = float(jnp.mean(d_ach)) / max(float(jnp.mean(d_exact)), 1e-30)
    match = float(jnp.mean((idx_ach == idx_exact).astype(jnp.float32)))
    return round(ratio, 4), round(match, 4)


def main():
    max_size = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
    # 1024^2 is the CALIBRATION row: its field is independently known
    # good (35.9 dB PSNR vs the full exact-synthesis oracle, bench.py),
    # so its probe numbers anchor what ratio/match a ">=35 dB field"
    # produces under this metric.
    for size in (1024, 2048, 4096):
        if size > max_size:
            break
        a, ap, b = super_resolution(size)
        a, ap, b = (jnp.asarray(x, jnp.float32) for x in (a, ap, b))
        for x in (a, ap, b):
            _sync(x)
        cfg = SynthConfig(
            levels=6 if size > 1024 else 5, matcher="patchmatch",
            em_iters=2, pm_iters=6,
        )
        _sync(create_image_analogy(a, ap, b, cfg))  # compile
        walls = []
        for _ in range(2):
            t0 = time.perf_counter()
            out = create_image_analogy(a, ap, b, cfg)
            _sync(out)
            walls.append(round(time.perf_counter() - t0, 2))

        fd, path = tempfile.mkstemp(suffix=".jsonl")
        os.close(fd)
        level_ms, energy = [], None
        try:
            # One instrumented run yields both the per-level walls AND
            # the aux fields the probe needs (same run, not merely the
            # same seed).
            aux = create_image_analogy(
                a, ap, b, cfg, return_aux=True,
                progress=ProgressWriter(path),
            )
            _sync(aux["bp"])
            for line in open(path):
                rec = json.loads(line)
                if rec.get("event") == "level_done":
                    level_ms.append(rec["wall_ms"])
                    if rec["level"] == 0:
                        energy = rec["nnf_energy"]
        finally:
            os.unlink(path)

        ratio, match = _exact_probe(a, ap, b, cfg, aux)

        row = {
            "size": size,
            "wall_s": min(walls),
            "wall_runs_s": walls,
            "level_wall_ms": level_ms,
            "nnf_energy_level0": energy,
            "exact_probe_pixels": _N_PROBE,
            "dist_ratio_vs_exact": ratio,
            "exact_match_frac": match,
        }
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
