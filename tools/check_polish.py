#!/usr/bin/env python
"""Validate a POLISH_r08.json round artifact (the DMA-streamed polish
probe record) — the tools/check_bench.py discipline applied to the
round-8 decision artifact, so the acceptance criteria ("a measured
interpret/XLA-oracle bit-identity result, the modeled bytes/roofline
vs the gather floor, a pre-stated kill criterion, and the hardware A/B
recipe") are enforced by a validator instead of trusted to prose.

Usage:
    python tools/check_polish.py POLISH_r08.json

Runs under pytest too (tests/test_check_bench.py TestCheckPolish
validates the COMMITTED artifact) so tier-1 fails if the record is
missing, truncated, or structurally degraded.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List

_POLISH_MODES = ("sequential", "jump", "stream")


def _num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def validate_polish(record: dict) -> List[str]:
    """Return a list of violations (empty = valid)."""
    errs: List[str] = []
    if not isinstance(record, dict):
        return ["record is not a JSON object"]

    dec = record.get("decision")
    if not isinstance(dec, dict):
        errs.append("decision: missing object")
        dec = {}
    if not isinstance(dec.get("default_mode"), str) or (
        dec.get("default_mode") not in _POLISH_MODES
    ):
        errs.append(
            f"decision.default_mode {dec.get('default_mode')!r} names "
            f"none of {_POLISH_MODES}"
        )
    if not isinstance(dec.get("kill_criterion_prestated"), str) or not (
        dec.get("kill_criterion_prestated") or ""
    ).strip():
        errs.append("decision.kill_criterion_prestated: missing/empty")

    meas = record.get("measured_this_round")
    if not isinstance(meas, dict):
        errs.append("measured_this_round: missing object")
        meas = {}
    for key in (
        "stream_bit_identical_standard_path",
        "stream_bit_identical_lean_path",
    ):
        if not isinstance(meas.get(key), bool):
            errs.append(f"measured_this_round.{key}: missing boolean")
        elif meas[key] is not True:
            errs.append(
                f"measured_this_round.{key} is false — the streamed "
                "polish must not ship without bit-identity"
            )
    if not isinstance(meas.get("bit_identity_backend"), str):
        errs.append("measured_this_round.bit_identity_backend: missing")

    bm = record.get("byte_model")
    if not isinstance(bm, dict):
        errs.append("byte_model: missing object")
        bm = {}
    pf = bm.get("per_fetch_bytes")
    if not isinstance(pf, dict):
        errs.append("byte_model.per_fetch_bytes: missing object")
    else:
        moved, useful = pf.get("moved"), pf.get("useful")
        if not (_num(moved) and _num(useful) and 0 < useful <= moved):
            errs.append(
                f"byte_model.per_fetch_bytes moved={moved!r} "
                f"useful={useful!r} violate 0 < useful <= moved"
            )

    proj = record.get("projection_modeled_not_measured")
    if not isinstance(proj, dict):
        errs.append("projection_modeled_not_measured: missing object")
        proj = {}
    wall = proj.get("projected_wall_4096_s")
    if not (_num(wall) and wall > 0):
        errs.append(
            f"projection.projected_wall_4096_s {wall!r} is not a "
            "positive number"
        )
    if not isinstance(proj.get("gap_attribution"), dict):
        errs.append("projection.gap_attribution: missing object")

    recipe = record.get("hardware_recipe")
    if not isinstance(recipe, dict) or not isinstance(
        recipe.get("tool"), str
    ):
        errs.append("hardware_recipe.tool: missing")
    return errs


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("record", help="path to POLISH_r08.json")
    args = ap.parse_args(argv)
    try:
        with open(args.record) as f:
            record = json.load(f)
    except (OSError, ValueError) as e:
        print(f"check_polish: cannot read {args.record}: {e}",
              file=sys.stderr)
        return 2
    errs = validate_polish(record)
    if errs:
        for e in errs:
            print(f"check_polish: {e}", file=sys.stderr)
        print(
            f"check_polish: FAIL — {len(errs)} violation(s) in "
            f"{args.record}", file=sys.stderr,
        )
        return 1
    print(
        "check_polish: OK — default_mode="
        f"{record['decision']['default_mode']}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
