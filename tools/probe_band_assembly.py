"""Measured probe: per-band lean A-table assembly peak memory.

VERDICT r4 task 7 asks for the band-sharded assembly's memory claim to
be MEASURED, not asserted: with n bands, each device assembles only a
halo-extended slab, so its peak assembly footprint should be ~1/n of
the single-device full assembly (plus the halo overhead and the
resident band slice).

Only one real chip exists here, so the probe measures the per-device
work directly: assemble the FULL table at `size`, then assemble ONE
band's slab (rows/n + 2*halo rows) — exactly the computation
`parallel/sharded_a._band_assemble_fn` runs per device — and compare
peak memory, one fresh process per phase so peaks are independent.
By default it runs on the CPU backend (never attaching a second
client to the tunnelled TPU) and reports the process's maxrss growth
across the assembly call; `PROBE_DEVICE=tpu` opts into the chip's
allocator `peak_bytes_in_use` when the chip is free — but note the
tunnelled axon backend does NOT forward real allocator peaks
(measured 2026-08-01: it reports ~15-48 MB for 1-GB-scale
assemblies), so on this environment the CPU default is the
meaningful measurement.  The
maxrss window includes the jit compile's near-constant memory, so the
ratio is only meaningful when the table dwarfs it — probe at
size >= 2048 (at 2048x2048/8 bands the measured ratio is 0.129 vs
the 0.125 ideal; at 256x256 compile overhead dominates and the ratio
is meaningless).

    python tools/probe_band_assembly.py 2048 8      # one phase per call
    python tools/probe_band_assembly.py 2048 8 full
    python tools/probe_band_assembly.py 2048 8 band
"""

import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _measure(size: int, n_bands: int, phase: str) -> dict:
    import numpy as np
    import jax

    # Default to the CPU backend BEFORE first device use: this probe
    # measures per-device assembly footprint scaling, which is
    # structural, and it must never attach a second client to the
    # tunnelled TPU while a long oracle run holds it (sitecustomize pins
    # jax_platforms=axon,cpu, so the env var alone is ignored — the
    # in-process override is the reliable one, same as
    # tests/conftest.py).  PROBE_DEVICE=tpu opts into the real-chip
    # allocator-stats measurement when the chip is free.
    if os.environ.get("PROBE_DEVICE", "cpu") != "tpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from image_analogies_tpu.config import SynthConfig
    from image_analogies_tpu.models.analogy import assemble_features_lean
    from image_analogies_tpu.parallel.spatial import slab_halo
    from image_analogies_tpu.utils.cache import enable_compilation_cache
    from image_analogies_tpu.utils.kernelbench import sync

    enable_compilation_cache()
    cfg = SynthConfig()
    halo = slab_halo(cfg)
    rng = np.random.default_rng(0)
    if phase == "full":
        rows = size
        rows_c = size // 2
    else:
        rows = size // n_bands + 2 * halo
        rows_c = size // (2 * n_bands) + halo
    src = jnp.asarray(rng.random((rows, size), np.float32))
    flt = jnp.asarray(rng.random((rows, size), np.float32))
    src_c = jnp.asarray(rng.random((rows_c, size // 2), np.float32))
    flt_c = jnp.asarray(rng.random((rows_c, size // 2), np.float32))
    for x in (src, flt, src_c, flt_c):
        sync(x)
    import resource

    dev = jax.devices()[0]
    base = (dev.memory_stats() or {}).get("peak_bytes_in_use", 0)
    rss_before = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    tab = jax.jit(
        lambda *a: assemble_features_lean(a[0], a[1], cfg, a[2], a[3])
    )(src, flt, src_c, flt_c)
    sync(tab)
    stats = dev.memory_stats() or {}
    peak = stats.get("peak_bytes_in_use", -1)
    if peak <= 0:
        # CPU backend (or a backend that doesn't forward allocator
        # stats): buffers live in host memory, so the process's maxrss
        # growth across the assembly call is the assembly-attributable
        # peak.  The two phases run in fresh identical processes, so
        # the interpreter/jax baseline cancels in the delta.
        rss_after = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
        peak = rss_after - rss_before
    return {
        "phase": phase,
        "rows": int(rows),
        "table_shape": [int(s) for s in tab.shape],
        "peak_bytes_in_use": int(stats.get("peak_bytes_in_use", -1)),
        "peak_before_mb": round(base / 1e6, 1),
        "peak_after_mb": round(peak / 1e6, 1),
    }


def main():
    size = int(sys.argv[1]) if len(sys.argv) > 1 else 2048
    n_bands = int(sys.argv[2]) if len(sys.argv) > 2 else 8
    if len(sys.argv) > 3:
        print(json.dumps(_measure(size, n_bands, sys.argv[3])), flush=True)
        return
    # Driver mode: one fresh process per phase so allocator peaks are
    # independent.
    out = {}
    for phase in ("full", "band"):
        res = subprocess.run(
            [sys.executable, __file__, str(size), str(n_bands), phase],
            capture_output=True, text=True,
        )
        if res.returncode != 0 or not res.stdout.strip():
            sys.stderr.write(res.stderr)
            raise SystemExit(
                f"phase {phase!r} failed (rc={res.returncode}); "
                "stderr above"
            )
        line = res.stdout.strip().splitlines()[-1]
        out[phase] = json.loads(line)
    ratio = (
        out["band"]["peak_after_mb"] / out["full"]["peak_after_mb"]
        if out["full"]["peak_after_mb"] > 0 else None
    )
    print(json.dumps({
        "size": size,
        "n_bands": n_bands,
        "full_peak_mb": out["full"]["peak_after_mb"],
        "band_peak_mb": out["band"]["peak_after_mb"],
        "band_over_full": round(ratio, 3) if ratio is not None else None,
        "ideal": round(1 / n_bands, 3),
    }), flush=True)


if __name__ == "__main__":
    main()
