#!/usr/bin/env python
"""Validate a VIDEO_r14.json video-analogies artifact (round 14).

The video acceptance bar, enforced by a validator instead of trusted to
prose: on a >= 8-frame sequence at a >= 64px proxy, every frame after
the first must have warm-started (warm_frames == frames - 1) on a
measurably shortened schedule (modeled warm_cost_ratio <= 0.6, the
delta-cost claim), the warm pass must hold the static-scene quality
gate (mean PSNR-vs-oracle within 0.1 dB of the cold pass), the
temporal-coherence term must have actually reduced flicker (warm_tau
strictly below independent per-frame synthesis), the warm-start sweep
ledger must reconcile with itself and with the frame counts, and the
sentinel's `warm_start` check must have graded both warm passes "ok" —
a ledger the engine's own invariant check rejects is not an artifact,
it is a bug report.

Usage:
    python tools/check_video.py VIDEO_r14.json

Runs under pytest too (tests/test_video.py validates the COMMITTED
artifact) so tier-1 fails if the record is missing, truncated, or
structurally degraded.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List

VIDEO_SCHEMA_VERSION = 1

WARM_COST_RATIO_MAX = 0.6
QUALITY_DELTA_DB_MIN = -0.1


def _num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _label_sum(counter, label: str = None) -> float:
    """Sum a metrics-snapshot counter dict ({label_repr: value}),
    optionally restricted to entries mentioning `label`."""
    if not isinstance(counter, dict):
        return float("nan")
    total = 0.0
    for k, v in counter.items():
        if label is not None and label not in k:
            continue
        if not _num(v):
            return float("nan")
        total += v
    return total


def validate_video(record: dict) -> List[str]:
    """Return a list of violations (empty = valid)."""
    errs: List[str] = []
    if not isinstance(record, dict):
        return ["record is not a JSON object"]
    if record.get("schema_version") != VIDEO_SCHEMA_VERSION:
        errs.append(
            f"schema_version {record.get('schema_version')!r} != "
            f"{VIDEO_SCHEMA_VERSION}"
        )
    if record.get("kind") != "video":
        errs.append(f"kind {record.get('kind')!r} != 'video'")
    size = record.get("proxy_size")
    if not (_num(size) and size >= 64):
        errs.append(f"proxy_size {size!r} is not a size >= 64")
    frames = record.get("frames")
    if not (_num(frames) and frames >= 8):
        errs.append(f"frames {frames!r} is not a count >= 8")
        frames = None

    cold = record.get("cold")
    if not isinstance(cold, dict):
        errs.append("cold: missing object")
        cold = {}
    warm = record.get("warm")
    if not isinstance(warm, dict):
        errs.append("warm: missing object")
        warm = {}
    if frames is not None:
        for sect, d in (("cold", cold), ("warm", warm)):
            walls = d.get("wall_s_per_frame")
            if not (isinstance(walls, list) and len(walls) == frames
                    and all(_num(w) and w >= 0 for w in walls)):
                errs.append(
                    f"{sect}.wall_s_per_frame is not a list of "
                    f"{frames} non-negative numbers"
                )
        scheds = warm.get("schedules")
        if not (isinstance(scheds, list) and len(scheds) == frames):
            errs.append(f"warm.schedules is not a list of {frames}")
            scheds = None
        deltas = warm.get("deltas")
        if not (isinstance(deltas, list) and len(deltas) == frames):
            errs.append(f"warm.deltas is not a list of {frames}")
        elif deltas[0] is not None:
            errs.append(
                f"warm.deltas[0] {deltas[0]!r} is not null — frame 0 "
                "has nothing to warm-start from and must run cold"
            )
        wf = warm.get("warm_frames")
        if not (_num(wf) and wf == frames - 1):
            errs.append(
                f"warm.warm_frames {wf!r} != frames - 1 "
                f"({frames - 1}) — every frame after the first must "
                "warm-start on this bench's static scene"
            )
        cfg = record.get("config")
        if not isinstance(cfg, dict):
            errs.append("config: missing object")
        elif scheds:
            full = [cfg.get("pm_iters"), cfg.get("em_iters")]
            if list(scheds[0]) != full:
                errs.append(
                    f"warm.schedules[0] {scheds[0]!r} != cold schedule "
                    f"{full!r} — frame 0 must run the full schedule"
                )
            shortened = [
                s for s in scheds[1:]
                if isinstance(s, list) and s != full
            ]
            if not shortened:
                errs.append(
                    "no warm frame ran a shortened schedule — the "
                    "delta-cost scheduler never engaged"
                )

    ratio = warm.get("warm_cost_ratio")
    if not (_num(ratio) and 0.0 < ratio <= WARM_COST_RATIO_MAX):
        errs.append(
            f"warm.warm_cost_ratio {ratio!r} is not in "
            f"(0, {WARM_COST_RATIO_MAX}] — warm frames must run a "
            "measurably reduced modeled schedule"
        )
    ru, cu = warm.get("run_units"), warm.get("cold_units")
    if _num(ru) and _num(cu) and cu > 0 and _num(ratio):
        if abs(ru / cu - ratio) > 0.01:
            errs.append(
                f"warm.warm_cost_ratio {ratio} != run_units/cold_units "
                f"({ru}/{cu}) — the ratio must come from the model it "
                "claims to"
            )

    quality = record.get("quality")
    if not isinstance(quality, dict):
        errs.append("quality: missing object")
        quality = {}
    mean_d = quality.get("mean_delta_db")
    if not (_num(mean_d) and mean_d >= QUALITY_DELTA_DB_MIN):
        errs.append(
            f"quality.mean_delta_db {mean_d!r} is not >= "
            f"{QUALITY_DELTA_DB_MIN} — the warm pass must hold PSNR-vs-"
            "oracle within 0.1 dB of the cold pass"
        )
    for k in ("psnr_cold_db", "psnr_warm_db"):
        arr = quality.get(k)
        if frames is not None and not (
            isinstance(arr, list) and len(arr) == frames
            and all(_num(p) for p in arr)
        ):
            errs.append(f"quality.{k} is not a list of {frames} numbers")

    flick = record.get("flicker")
    if not isinstance(flick, dict):
        errs.append("flicker: missing object")
        flick = {}
    indep, wtau = flick.get("independent"), flick.get("warm_tau")
    if not (_num(indep) and _num(wtau) and wtau < indep):
        errs.append(
            f"flicker.warm_tau {wtau!r} is not strictly below "
            f"flicker.independent {indep!r} — the coherence term must "
            "demonstrably reduce flicker vs per-frame synthesis"
        )
    tau = flick.get("tau")
    if not (_num(tau) and tau > 0):
        errs.append(f"flicker.tau {tau!r} is not > 0")

    ledger = record.get("ledger")
    if not isinstance(ledger, dict):
        errs.append("ledger: missing object")
        ledger = {}
    warm_booked = _label_sum(
        ledger.get("ia_warm_start_frames_total")
    )
    frames_warm = _label_sum(
        ledger.get("ia_video_frames_total"), 'mode="warm"'
    )
    if warm_booked != frames_warm:
        errs.append(
            f"ledger: ia_warm_start_frames_total {warm_booked} != "
            f"ia_video_frames_total{{mode=warm}} {frames_warm}"
        )
    wf = warm.get("warm_frames")
    if _num(wf) and warm_booked != wf:
        errs.append(
            f"ledger: ia_warm_start_frames_total {warm_booked} != "
            f"warm.warm_frames {wf}"
        )
    sw = ledger.get("ia_warm_start_sweeps_total")
    sw_warm = _label_sum(sw, 'mode="warm"')
    sw_cold = _label_sum(sw, 'mode="cold_equiv"')
    if not (sw_warm == sw_warm and sw_cold == sw_cold):  # NaN guard
        errs.append("ledger: ia_warm_start_sweeps_total is malformed")
    elif sw_warm >= sw_cold:
        errs.append(
            f"ledger: warm sweeps {sw_warm} >= cold-equivalent "
            f"{sw_cold} — the warm schedule saved nothing"
        )

    for k in ("warm_check", "warm_check_tau"):
        if record.get(k) != "ok":
            errs.append(
                f"{k} {record.get(k)!r} != 'ok' — the sentinel's "
                "warm_start invariants must grade the run clean"
            )
    return errs


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="VIDEO_r14.json to validate")
    args = ap.parse_args(argv)
    try:
        with open(args.path) as f:
            record = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"check_video: cannot read {args.path}: {e}")
        return 1
    errs = validate_video(record)
    if errs:
        print(f"check_video: {args.path} INVALID:")
        for e in errs:
            print(f"  - {e}")
        return 1
    warm = record.get("warm", {})
    flick = record.get("flicker", {})
    print(
        f"check_video: {args.path} OK "
        f"(warm_cost_ratio={warm.get('warm_cost_ratio')}, quality "
        f"delta {record.get('quality', {}).get('mean_delta_db')} dB, "
        f"flicker {flick.get('independent')} -> {flick.get('warm_tau')}"
        f" at tau={flick.get('tau')})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
