"""Batch x lean composition bench: 8 frames of 2048^2 through the
batched runner on one chip (round-3 VERDICT task 4's measured row).

Each 2048^2 frame's f32 feature tables exceed the default
`feature_bytes_budget`, so `_batch_level_fn` takes the LEAN branch
(per-frame plane-pair NN fields, bf16 chunk-assembled tables) at the
fine levels — the same composition tests/test_pallas_patchmatch.py
pins with a forced-tiny budget and counted `tile_patchmatch_lean`
calls; this harness measures it at the real scale the budget actually
trips at.  `frames_per_step=1` microbatches HBM exactly like the
config-5 bench row (bench.py).

Prints one JSON line:  python tools/batch_scale_bench.py [n_frames]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax.numpy as jnp

from image_analogies_tpu.utils.cache import enable_compilation_cache

enable_compilation_cache()

from image_analogies_tpu import SynthConfig
from image_analogies_tpu.parallel.batch import synthesize_batch
from image_analogies_tpu.parallel.mesh import make_mesh
from image_analogies_tpu.utils.examples import npr_frames
from image_analogies_tpu.utils.kernelbench import sync as _sync

_SIZE = 2048


def main():
    n_frames = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    a, ap, frames = npr_frames(n_frames=n_frames, size=_SIZE)
    a, ap, frames = (jnp.asarray(x, jnp.float32) for x in (a, ap, frames))
    for x in (a, ap, frames):
        _sync(x)

    cfg = SynthConfig(levels=6, matcher="patchmatch", em_iters=2, kappa=2.0)
    mesh = make_mesh()
    fn = lambda: synthesize_batch(  # noqa: E731
        a, ap, frames, cfg, mesh, frames_per_step=1
    )
    _sync(fn())  # compile
    walls = []
    for _ in range(2):
        t0 = time.perf_counter()
        out = fn()
        _sync(out)
        walls.append(round(time.perf_counter() - t0, 2))

    print(
        json.dumps(
            {
                "config": f"batched-npr-{n_frames}x{_SIZE}-fps1-lean",
                "wall_s": min(walls),
                "wall_runs_s": walls,
                "per_frame_s": round(min(walls) / n_frames, 2),
                "out_shape": list(out.shape),
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
