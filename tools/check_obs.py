#!/usr/bin/env python
"""Validate an OBS_r19.json serving-observatory artifact (round 19).

The fleet-aggregation acceptance bar, enforced by arithmetic instead
of trusted to prose: the committed record must carry >= 2 scraped
replicas, each with its request-duration histogram family, and a
fleet section whose SLO report is BIT-EQUAL to re-deriving it here —
re-merge the per-replica families (sum counters, pool histogram cells
bucket-by-bucket) and re-run the round-15 objective grading over the
pooled cells.  Any divergence means the aggregator averaged where it
should have pooled, dropped a label set, or mangled a bucket — the
exact failure modes fleet dashboards silently absorb.

Also pinned: the observatory's measured request-path overhead
(`observatory_overhead_frac`, the paired obs-on/obs-off arms in
tools/serve_load.py --obs-out) must sit under the telemetry budget
the sentinel watches (2%), and each replica's windowed view must be a
structurally valid obs_window (status ok / single_snapshot / no_data,
never an invented rate).

Usage:
    python tools/check_obs.py OBS_r19.json

Runs under pytest too (tests/test_observatory.py validates the
COMMITTED artifact) so tier-1 fails if the record is missing,
truncated, or its fleet arithmetic stops reproducing.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_HERE)
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

OBS_SCHEMA_VERSION = 1
OVERHEAD_BUDGET_FRAC = 0.02
DURATION_METRIC = "ia_request_duration_ms"
_WINDOW_STATUSES = ("ok", "single_snapshot", "no_data")


def _num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def _validate_window(window, where: str, errs: List[str]) -> None:
    if window is None:
        return  # stated absence (replica predates /obs/window)
    if not isinstance(window, dict):
        errs.append(f"{where}: window is not an object")
        return
    if window.get("kind") != "obs_window":
        errs.append(f"{where}: window.kind {window.get('kind')!r}")
    status = window.get("status")
    if status not in _WINDOW_STATUSES:
        errs.append(f"{where}: window.status {status!r}")
        return
    if status == "no_data":
        for section in ("counters", "gauges", "histograms"):
            if window.get(section):
                errs.append(
                    f"{where}: no_data window has non-empty {section} "
                    "(absence must be stated, never imputed)"
                )
    if status != "ok":
        # Rates must be null, not invented, without a delta base.
        for fam in (window.get("counters") or {}).values():
            for cell in fam.values():
                if cell.get("rate_per_s") is not None:
                    errs.append(
                        f"{where}: {status} window carries a counter "
                        "rate (imputed rate without a base)"
                    )
                    return


def validate_obs(record: dict) -> List[str]:
    """Return a list of violations (empty = valid)."""
    from image_analogies_tpu.serving.observatory import (
        fleet_slo,
        merge_registries,
    )

    errs: List[str] = []
    if not isinstance(record, dict):
        return ["record is not a JSON object"]
    if record.get("schema_version") != OBS_SCHEMA_VERSION:
        errs.append(
            f"schema_version {record.get('schema_version')!r} != "
            f"{OBS_SCHEMA_VERSION}"
        )
    if record.get("kind") != "obs":
        errs.append(f"kind {record.get('kind')!r} != 'obs'")
    rnd = record.get("round")
    if not (_num(rnd) and rnd >= 19):
        errs.append(f"round {rnd!r} is not a round >= 19")

    replicas = record.get("replicas")
    if not isinstance(replicas, list) or len(replicas) < 2:
        errs.append(
            f"replicas: need >= 2 scraped replicas, got "
            f"{len(replicas) if isinstance(replicas, list) else replicas!r}"
        )
        return errs
    live = []
    for i, rep in enumerate(replicas):
        where = f"replicas[{i}]"
        if not isinstance(rep, dict) or not rep.get("url"):
            errs.append(f"{where}: missing url")
            continue
        if rep.get("error"):
            continue
        live.append(rep)
        metrics = rep.get("metrics")
        if not isinstance(metrics, dict):
            errs.append(f"{where}: missing metrics")
            continue
        fam = metrics.get(DURATION_METRIC) or {}
        if not (fam.get("values") or {}):
            errs.append(
                f"{where}: no {DURATION_METRIC} observations (replica "
                "saw no traffic — the artifact must be cut under load)"
            )
        slo = rep.get("slo")
        if not isinstance(slo, dict) or slo.get("kind") != "slo":
            errs.append(f"{where}: missing /slo report")
        _validate_window(rep.get("window"), where, errs)
    if len(live) < 2:
        errs.append(f"fewer than 2 live replicas ({len(live)})")
        return errs

    fleet = record.get("fleet")
    if not isinstance(fleet, dict):
        errs.append("missing fleet section")
        return errs
    if fleet.get("replicas_live") != len(live):
        errs.append(
            f"fleet.replicas_live {fleet.get('replicas_live')!r} != "
            f"{len(live)} live replicas present"
        )

    # -- the pooling contract: recompute and require bit-equality ----
    recomputed = fleet_slo(
        merge_registries([r["metrics"] for r in live])
    )
    committed = fleet.get("slo")
    if committed != recomputed:
        errs.append(
            "fleet.slo is NOT bit-equal to re-merging the per-replica "
            "histograms and re-grading (pooled-not-averaged contract "
            "broken); diverging keys: "
            + _diff_keys(committed, recomputed)
        )
    else:
        for obj in (committed or {}).get("objectives", []):
            if obj.get("status") in ("exhausted",):
                errs.append(
                    f"fleet objective {obj.get('name')}: error budget "
                    f"exhausted in the committed artifact "
                    f"(burn {obj.get('burn_rate')})"
                )

    overhead = record.get("observatory_overhead_frac")
    if not _num(overhead):
        errs.append(
            f"observatory_overhead_frac {overhead!r} is not a number "
            "(the < 2% pin needs a measurement)"
        )
    elif not 0.0 <= overhead < OVERHEAD_BUDGET_FRAC:
        errs.append(
            f"observatory_overhead_frac {overhead} outside "
            f"[0, {OVERHEAD_BUDGET_FRAC})"
        )
    return errs


def _diff_keys(a, b) -> str:
    if not isinstance(a, dict) or not isinstance(b, dict):
        return f"{type(a).__name__} vs {type(b).__name__}"
    out = []
    for k in sorted(set(a) | set(b)):
        if a.get(k) != b.get(k):
            out.append(k)
    return ", ".join(out) or "(none — container mismatch)"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("record", help="path to OBS_r19.json")
    args = ap.parse_args(argv)
    try:
        with open(args.record, "r", encoding="utf-8") as fh:
            record = json.load(fh)
    except (OSError, ValueError) as e:
        print(f"check_obs: cannot read {args.record}: {e}",
              file=sys.stderr)
        return 2
    errs = validate_obs(record)
    if errs:
        print(f"check_obs: {args.record}: {len(errs)} violation(s):")
        for e in errs:
            print(f"  - {e}")
        return 1
    fleet_verdict = ((record.get("fleet") or {}).get("slo") or {}) \
        .get("verdict")
    print(
        f"check_obs: {args.record} OK — "
        f"{(record.get('fleet') or {}).get('replicas_live')} replicas, "
        f"fleet verdict {fleet_verdict}, overhead "
        f"{record.get('observatory_overhead_frac')}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
