#!/usr/bin/env python
"""Validate a FAULTS_r12.json chaos-suite artifact (round 12).

The supervised-execution acceptance bar, enforced by a validator
instead of trusted to prose: every fault class in the matrix must end
in exactly one of the three declared outcomes, a healed arm must be
bit-identical to the undisturbed run, a degraded arm must have
RECORDED its ladder steps (and its health verdict must say degraded —
a degradation that grades clean is the silent-quality-loss failure
mode this round exists to prevent), and NO fault class may end in an
unvalidated death: a gave-up arm without a schema-valid flight dump is
a run that died without a post-mortem.

Usage:
    python tools/check_faults.py FAULTS_r12.json

Runs under pytest too (tests/test_faults.py validates the COMMITTED
artifact) so tier-1 fails if the record is missing, truncated, or
structurally degraded.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List

FAULTS_SCHEMA_VERSION = 1
_OUTCOMES = ("healed", "degraded", "clean_death")
# Every IA_FAULT_PLAN action class must appear in the matrix, plus at
# least one arm that exercises the give-up path end-to-end.
_REQUIRED_CLASSES = ("raise", "hang", "truncate", "fail", "clean_death")


def _num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def validate_faults(record: dict) -> List[str]:
    """Return a list of violations (empty = valid)."""
    errs: List[str] = []
    if not isinstance(record, dict):
        return ["record is not a JSON object"]
    if record.get("schema_version") != FAULTS_SCHEMA_VERSION:
        errs.append(
            f"schema_version {record.get('schema_version')!r} != "
            f"{FAULTS_SCHEMA_VERSION}"
        )
    if record.get("kind") != "faults":
        errs.append(f"kind {record.get('kind')!r} != 'faults'")
    size = record.get("proxy_size")
    if not (_num(size) and size >= 16):
        errs.append(f"proxy_size {size!r} is not a size >= 16")

    classes = record.get("classes_covered")
    if not isinstance(classes, list):
        errs.append("classes_covered: missing list")
        classes = []
    for cls in _REQUIRED_CLASSES:
        if cls not in classes:
            errs.append(
                f"classes_covered is missing {cls!r} — the matrix "
                "must exercise every fault class"
            )

    arms = record.get("arms")
    if not isinstance(arms, list) or not arms:
        errs.append("arms: missing/empty list")
        arms = []
    for i, arm in enumerate(arms):
        if not isinstance(arm, dict):
            errs.append(f"arms[{i}]: not an object")
            continue
        name = arm.get("name", f"arms[{i}]")
        outcome = arm.get("outcome")
        if outcome not in _OUTCOMES:
            errs.append(
                f"{name}: outcome {outcome!r} names none of "
                f"{_OUTCOMES} — an undeclared ending is an "
                "unvalidated death"
            )
            continue
        if arm.get("expected_outcome") not in _OUTCOMES:
            errs.append(
                f"{name}: expected_outcome "
                f"{arm.get('expected_outcome')!r} names none of "
                f"{_OUTCOMES}"
            )
        elif outcome != arm["expected_outcome"]:
            errs.append(
                f"{name}: outcome {outcome!r} != expected "
                f"{arm['expected_outcome']!r}"
            )
        if not isinstance(arm.get("fault_plan"), str) or not arm.get(
            "fault_plan"
        ):
            errs.append(f"{name}: fault_plan missing/empty")
        if outcome == "healed":
            if arm.get("bit_identical") is not True:
                errs.append(
                    f"{name}: healed but bit_identical is "
                    f"{arm.get('bit_identical')!r} — a heal that "
                    "changes the output is not a heal"
                )
            if arm.get("recovery_check") not in ("ok",):
                errs.append(
                    f"{name}: healed but the sentinel recovery check "
                    f"graded {arm.get('recovery_check')!r}"
                )
        if outcome == "degraded":
            d = arm.get("degradations")
            if not (_num(d) and d >= 1):
                errs.append(
                    f"{name}: degraded with degradations={d!r} — a "
                    "ladder step must be recorded, never silent"
                )
            if arm.get("recovery_check") != "degraded":
                errs.append(
                    f"{name}: degraded arm's recovery check graded "
                    f"{arm.get('recovery_check')!r} — a degradation "
                    "must never pass as clean"
                )
        if outcome == "clean_death":
            if arm.get("gave_up") is not True:
                errs.append(
                    f"{name}: clean_death without gave_up=true"
                )
            if arm.get("flight_validated") is not True:
                errs.append(
                    f"{name}: clean_death WITHOUT a validated flight "
                    "dump — an unvalidated death, the one ending the "
                    "acceptance criteria forbid"
                )
        else:
            # Survivors: overhead must be a recorded non-negative
            # fraction (the recovery price is part of the artifact's
            # claim).
            ov = arm.get("recovery_overhead_frac")
            if not (_num(ov) and ov >= 0):
                errs.append(
                    f"{name}: recovery_overhead_frac {ov!r} is not a "
                    "non-negative number"
                )
        # Any arm that died must carry a validated dump, whatever the
        # outcome label claims (belt and braces for hand-edited
        # records).
        if arm.get("gave_up") is True and arm.get(
            "flight_validated"
        ) is not True:
            errs.append(
                f"{name}: gave_up without a validated flight dump"
            )
    return errs


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="FAULTS_r12.json to validate")
    args = ap.parse_args(argv)
    try:
        with open(args.path) as f:
            record = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"check_faults: cannot read {args.path}: {e}")
        return 1
    errs = validate_faults(record)
    if errs:
        print(f"check_faults: {args.path} INVALID:")
        for e in errs:
            print(f"  - {e}")
        return 1
    print(
        f"check_faults: {args.path} OK "
        f"({len(record.get('arms', []))} arms, classes: "
        f"{', '.join(record.get('classes_covered', []))})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
