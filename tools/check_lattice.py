#!/usr/bin/env python
"""Validate a LATTICE_r20.json shape-lattice artifact (round 20).

The shape-lattice acceptance bar, enforced by a validator instead of
trusted to prose:

  - bounded keys: the never-seen-shape burst must add ZERO executable
    cache entries beyond the warmed grid — exec-key cardinality is
    the lattice's, not the traffic's — and the grid itself must be
    fully resident after warmup (warm-before-announce covers every
    in-bounds shape);
  - hit-everything: every burst request (arbitrary never-seen shapes,
    a 1x1 degenerate, an exact bucket bound) is a cache HIT, with
    cold-shape p99 within 2x the warm p99 — the collapse from the
    ~24x compile-priced cold shapes SERVE_r18 measured;
  - bit-identity: the lattice's cropped output equals the unbucketed
    daemon's answer for the same frame edge-padded client-side
    (crop(serve(pad(F))) == lattice(F)), with zero mismatches, and an
    exactly-on-bucket frame byte-identical outright;
  - honest bypass: a frame over the top rung is a real MISS on the
    exact-key path, booked under path="bypass" — never a silent crop
    or an inflated hit rate;
  - recorded decision: the bucket geometry carries its planner
    provenance (chosen candidate + rejected field, or an explicit
    override) so the waste-vs-amortization trade is auditable.

Usage:
    python tools/check_lattice.py LATTICE_r20.json

Runs under pytest too (tests/test_lattice.py validates the COMMITTED
artifact) so tier-1 fails if the record is missing, truncated, or
claims a collapse it cannot show.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List

LATTICE_SCHEMA_VERSION = 1

# The acceptance criterion's latency bound: never-seen-shape p99 must
# sit within this multiple of the warm p99.
P99_COLD_OVER_WARM_MAX = 2.0


def _num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def validate_lattice(record: dict) -> List[str]:
    """Return a list of violations (empty = valid)."""
    errs: List[str] = []
    if not isinstance(record, dict):
        return ["record is not a JSON object"]
    if record.get("schema_version") != LATTICE_SCHEMA_VERSION:
        errs.append(
            f"schema_version {record.get('schema_version')!r} != "
            f"{LATTICE_SCHEMA_VERSION}"
        )
    if record.get("kind") != "lattice":
        errs.append(f"kind {record.get('kind')!r} != 'lattice'")

    # -- recorded decision ------------------------------------------
    plan = record.get("plan") or {}
    lat = plan.get("lattice") or {}
    rungs = lat.get("rungs")
    buckets = lat.get("buckets")
    if not (isinstance(rungs, list) and rungs
            and all(_num(r) for r in rungs)
            and rungs == sorted(rungs)
            and len(set(rungs)) == len(rungs)):
        errs.append(f"plan.lattice.rungs {rungs!r} is not a strictly "
                    "ascending rung ladder")
    if not (_num(buckets) and buckets >= 1):
        errs.append(f"plan.lattice.buckets {buckets!r} invalid")
    source = plan.get("source")
    if source not in ("planner", "override"):
        errs.append(f"plan.source {source!r} not in "
                    "('planner', 'override')")
    if source == "planner" and not plan.get("rejected"):
        errs.append(
            "plan.source is 'planner' but no rejected candidates are "
            "recorded — a decision with no alternatives is not a "
            "decision"
        )
    if not isinstance(plan.get("chosen"), dict):
        errs.append("plan.chosen missing — the priced winning "
                    "candidate must be recorded")

    # -- bounded keys ------------------------------------------------
    ek = record.get("exec_keys") or {}
    bound = ek.get("bound")
    warm_res = ek.get("resident_after_warmup")
    burst_res = ek.get("resident_after_burst")
    if not (_num(bound) and bound == buckets):
        errs.append(
            f"exec_keys.bound {bound!r} != plan.lattice.buckets "
            f"{buckets!r} — the bound must BE the lattice size"
        )
    if not (_num(warm_res) and warm_res == bound):
        errs.append(
            f"exec_keys.resident_after_warmup {warm_res!r} != bound "
            f"{bound!r} — warmup must precompile the WHOLE grid"
        )
    if not (_num(burst_res) and _num(warm_res)
            and burst_res == warm_res):
        errs.append(
            f"exec_keys.resident_after_burst {burst_res!r} != "
            f"resident_after_warmup {warm_res!r} — the never-seen "
            "burst grew the executable set: cardinality is not "
            "bounded by the lattice"
        )

    # -- hit-everything + the p99 bound -----------------------------
    burst = record.get("burst") or {}
    if burst.get("all_hits") is not True:
        errs.append("burst.all_hits is not true — a never-seen "
                    "in-bounds shape missed the warm grid")
    if not (_num(burst.get("requests")) and burst["requests"] >= 8):
        errs.append(
            f"burst.requests {burst.get('requests')!r} < 8 — the "
            "burst is too small to claim a p99"
        )
    shapes = burst.get("shapes")
    if not (isinstance(shapes, list)
            and any(s == [1, 1] for s in shapes)):
        errs.append("burst.shapes carries no 1x1 degenerate frame — "
                    "the lattice floor was never exercised")
    warm = record.get("warm") or {}
    p99_warm = warm.get("p99_ms")
    p99_cold = burst.get("p99_cold_ms")
    ratio = record.get("p99_cold_over_warm")
    if not (_num(p99_warm) and p99_warm > 0
            and _num(p99_cold) and p99_cold > 0):
        errs.append(
            f"warm.p99_ms {p99_warm!r} / burst.p99_cold_ms "
            f"{p99_cold!r} are not positive walls"
        )
    elif not (_num(ratio)
              and abs(ratio - p99_cold / p99_warm) < 0.01):
        errs.append(
            f"p99_cold_over_warm {ratio!r} does not match "
            f"p99_cold_ms/p99_warm_ms = {p99_cold / p99_warm:.4f}"
        )
    elif ratio > P99_COLD_OVER_WARM_MAX:
        errs.append(
            f"p99_cold_over_warm {ratio} > {P99_COLD_OVER_WARM_MAX} "
            "— never-seen shapes did not collapse to the warm "
            "envelope"
        )

    # -- bit-identity ------------------------------------------------
    ident = record.get("bit_identity") or {}
    if not (_num(ident.get("verified")) and ident["verified"] >= 3):
        errs.append(
            f"bit_identity.verified {ident.get('verified')!r} < 3 — "
            "the crop contract was never meaningfully compared"
        )
    if _num(ident.get("mismatched")) and ident["mismatched"]:
        errs.append(
            f"bit_identity.mismatched {ident['mismatched']} — a "
            "cropped output differs from the unbucketed path's "
            "answer for the padded frame"
        )
    if ident.get("mismatched") is None:
        errs.append("bit_identity.mismatched missing")
    if ident.get("on_bucket_identical") is not True:
        errs.append(
            "bit_identity.on_bucket_identical is not true — a frame "
            "already on a bucket shape must ride the lattice "
            "byte-identically to the lattice-off path"
        )

    # -- honest bypass ----------------------------------------------
    bypass = record.get("bypass") or {}
    if bypass.get("cache") != "miss":
        errs.append(
            f"bypass.cache {bypass.get('cache')!r} != 'miss' — an "
            "over-the-top-rung frame must pay an honest exact-key "
            "compile, not fake a hit"
        )
    if not (_num(bypass.get("admissions"))
            and bypass["admissions"] >= 1):
        errs.append(
            f"bypass.admissions {bypass.get('admissions')!r} — the "
            "bypass was never counted"
        )
    bypass_keys = ek.get("bypass_keys")
    if not (_num(bypass_keys) and bypass_keys >= 1):
        errs.append(
            f"exec_keys.bypass_keys {bypass_keys!r} — the bypass "
            "request left no exact-key cache entry"
        )

    # -- cardinality + sentinel -------------------------------------
    card = record.get("cardinality") or {}
    raw_c, buck_c = card.get("raw"), card.get("bucketed")
    if not (_num(raw_c) and _num(buck_c) and buck_c <= raw_c):
        errs.append(
            f"cardinality raw={raw_c!r} bucketed={buck_c!r} — "
            "bucketed cardinality must not exceed raw"
        )
    elif _num(bound) and _num(bypass_keys) \
            and buck_c > bound + bypass_keys:
        errs.append(
            f"cardinality.bucketed {buck_c} > lattice bound {bound} "
            f"+ bypass keys {bypass_keys}"
        )
    if record.get("serving_check") != "ok":
        errs.append(
            f"serving_check {record.get('serving_check')!r} != 'ok' "
            "— the admission/cache ledgers did not balance under the "
            "lattice"
        )
    return errs


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="LATTICE_r20.json to validate")
    args = ap.parse_args(argv)
    try:
        with open(args.path) as f:
            record = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"check_lattice: cannot read {args.path}: {e}")
        return 1
    errs = validate_lattice(record)
    if errs:
        print(f"check_lattice: {args.path} INVALID:")
        for e in errs:
            print(f"  - {e}")
        return 1
    ek = record.get("exec_keys", {})
    print(
        f"check_lattice: {args.path} OK "
        f"({ek.get('bound')} buckets, burst added "
        f"{ek.get('resident_after_burst', 0) - ek.get('resident_after_warmup', 0)} keys, "
        f"p99 cold/warm {record.get('p99_cold_over_warm')}x)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
