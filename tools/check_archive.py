#!/usr/bin/env python
"""Validate an ARCHIVE_r23.json durable-telemetry artifact (round 23).

The black-box acceptance bar, enforced by a validator instead of
trusted to prose:

  - baseline continuity: a daemon restarted with only `--archive-dir`
    resumes its anomaly watches against the PRE-restart baseline (the
    latency watch grades, never no_data), stamps a strictly later
    observatory generation, and the lineage renders through
    `ia-synth history`;
  - black-box capture: an induced anomaly episode yields EXACTLY ONE
    incident bundle — later firing ticks rate-limited and COUNTED as
    suppressed — containing every required section and renderable by
    `ia-synth incident <id>` both live (--url) and post-mortem
    (--archive-dir);
  - torn-tail tolerance: a SIGKILL mid-archive-append leaves a torn
    half-line that reload SKIPS and COUNTS, with baselines still
    resuming (the chaos arm from tools/chaos_serve.py);
  - bounded overhead: the archive write path's self-measured wall
    fraction stays under the shared 2% telemetry budget.

Usage:
    python tools/check_archive.py ARCHIVE_r23.json

Runs under pytest too (tests/test_archive.py validates the COMMITTED
artifact) so tier-1 fails if the record is missing, truncated, or
claims a continuity it cannot show.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List

ARCHIVE_DRILL_SCHEMA_VERSION = 1

# The shared telemetry-overhead ceiling (tools/check_sentinel.py's
# OVERHEAD_BUDGET_FRAC): the archive is one more observability
# surface, priced under the same budget.
OVERHEAD_CEILING_FRAC = 0.02

_REQUIRED_ARMS = (
    "restart_continuity",
    "incident_capture",
    "archive_torn_reload",
)


def _num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def validate_archive(record: dict) -> List[str]:
    """Return a list of violations (empty = valid)."""
    errs: List[str] = []
    if not isinstance(record, dict):
        return ["record is not a JSON object"]
    if record.get("schema_version") != ARCHIVE_DRILL_SCHEMA_VERSION:
        errs.append(
            f"schema_version {record.get('schema_version')!r} != "
            f"{ARCHIVE_DRILL_SCHEMA_VERSION}"
        )
    if record.get("kind") != "archive_drill":
        errs.append(f"kind {record.get('kind')!r} != 'archive_drill'")
    rnd = record.get("round")
    if not (_num(rnd) and rnd >= 23):
        errs.append(f"round {rnd!r} is not >= 23")
    size = record.get("proxy_size")
    if not (_num(size) and size >= 16):
        errs.append(f"proxy_size {size!r} is not a size >= 16")

    # Headline floors/ceilings.
    if record.get("baseline_continuity") != 1.0:
        errs.append(
            "baseline_continuity "
            f"{record.get('baseline_continuity')!r} != 1.0 — a "
            "restart that forgets its baselines is the cold-start "
            "the archive exists to prevent"
        )
    if record.get("capture_completeness") != 1.0:
        errs.append(
            "capture_completeness "
            f"{record.get('capture_completeness')!r} != 1.0 — an "
            "incident bundle missing a section is a black box that "
            "recorded half the flight"
        )
    if record.get("captured_bundles") != 1:
        errs.append(
            f"captured_bundles {record.get('captured_bundles')!r} "
            "!= 1 — one burn episode must yield exactly one bundle "
            "(zero is a miss, more is a rate-limiter failure)"
        )
    lat = record.get("capture_latency_ms")
    if not (_num(lat) and 0 < lat < 60000):
        errs.append(
            f"capture_latency_ms {lat!r} is not a positive "
            "sub-minute wall — the trigger-to-bundle delay is part "
            "of the claim"
        )
    ov = record.get("archive_overhead_frac")
    if not (_num(ov) and 0 <= ov < OVERHEAD_CEILING_FRAC):
        errs.append(
            f"archive_overhead_frac {ov!r} is not under the "
            f"{OVERHEAD_CEILING_FRAC:.0%} telemetry budget"
        )
    if record.get("torn_reload_clean") != 1.0:
        errs.append(
            f"torn_reload_clean {record.get('torn_reload_clean')!r} "
            "!= 1.0 — a torn tail that poisons reload defeats the "
            "durability idiom"
        )
    if record.get("generation_monotonic") != 1.0:
        errs.append(
            "generation_monotonic "
            f"{record.get('generation_monotonic')!r} != 1.0 — window "
            "epochs must never run backwards across a restart"
        )

    arms = record.get("arms")
    if not isinstance(arms, list) or not arms:
        return errs + ["arms: missing/empty list"]
    by_name = {
        arm.get("name"): arm for arm in arms if isinstance(arm, dict)
    }
    for need in _REQUIRED_ARMS:
        if need not in by_name:
            errs.append(
                f"arms is missing {need!r} — every continuity claim "
                "must be exercised"
            )
    if set(_REQUIRED_ARMS) - set(by_name):
        return errs  # per-arm checks need the arms present

    cont = by_name["restart_continuity"]
    if cont.get("baseline_resumed") is not True:
        errs.append(
            "restart_continuity: baseline_resumed is not true — "
            "boot 2 did not grade against boot 1's baseline"
        )
    if cont.get("watch_graded") is not True:
        errs.append(
            "restart_continuity: the post-restart latency watch "
            "reported no_data — the resumed baseline never reached "
            "the detector"
        )
    if not (_num(cont.get("history_boots"))
            and cont["history_boots"] >= 2):
        errs.append(
            f"restart_continuity: history_boots "
            f"{cont.get('history_boots')!r} < 2 — `ia-synth history` "
            "did not render the restart lineage"
        )
    for k in ("boot1_exit_code", "boot2_exit_code"):
        if cont.get(k) != 0:
            errs.append(
                f"restart_continuity: {k} {cont.get(k)!r} != 0 — "
                "the drill's graceful drains must exit clean"
            )

    inc = by_name["incident_capture"]
    if inc.get("rate_limited") is not True:
        errs.append(
            "incident_capture: rate_limited is not true — either no "
            "later tick was suppressed (the episode ended too soon "
            "to prove the limiter) or a duplicate bundle was written"
        )
    if inc.get("bundle_missing_keys"):
        errs.append(
            "incident_capture: bundle is missing sections "
            f"{inc['bundle_missing_keys']!r}"
        )
    for k in ("render_url_rc", "render_disk_rc"):
        if inc.get(k) != 0:
            errs.append(
                f"incident_capture: {k} {inc.get(k)!r} != 0 — "
                "`ia-synth incident` could not render the bundle"
            )

    torn = by_name["archive_torn_reload"]
    if torn.get("torn_line_appended") is not True:
        errs.append(
            "archive_torn_reload: torn_line_appended is not true — "
            "the arm must prove a torn tail is skipped, not absent"
        )
    if not (_num(torn.get("skipped_lines"))
            and torn["skipped_lines"] >= 1):
        errs.append(
            f"archive_torn_reload: skipped_lines "
            f"{torn.get('skipped_lines')!r} — the torn tail must be "
            "COUNTED on reload, not silently absorbed"
        )
    if torn.get("crash_exit_code") != 137:
        errs.append(
            "archive_torn_reload: crash_exit_code "
            f"{torn.get('crash_exit_code')!r} != 137 — the injected "
            "kill never landed mid-append"
        )
    if torn.get("post_restart_request_ok") is not True:
        errs.append(
            "archive_torn_reload: the restarted daemon did not "
            "serve a request after reloading past the torn tail"
        )
    return errs


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="ARCHIVE_r23.json to validate")
    args = ap.parse_args(argv)
    try:
        with open(args.path) as f:
            record = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"check_archive: cannot read {args.path}: {e}")
        return 1
    errs = validate_archive(record)
    if errs:
        print(f"check_archive: {args.path} INVALID:")
        for e in errs:
            print(f"  - {e}")
        return 1
    print(
        f"check_archive: {args.path} OK (continuity="
        f"{record.get('baseline_continuity')}, completeness="
        f"{record.get('capture_completeness')}, overhead_frac="
        f"{record.get('archive_overhead_frac')})"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
