"""Headline A/B: DMA-streamed polish vs the sequential XLA cascade
(`models/patchmatch._POLISH_MODE` "stream" vs "sequential") — the
round-8 decision gate, in the tools/polish_ab.py discipline.

KILL CRITERION, pre-stated: "stream" becomes the default iff, on
hardware at the 1024^2 headline schedule, (a) its median wall beats
sequential's, and (b) min-over-seeds PSNR-vs-oracle is unchanged —
which bit-identity guarantees a priori, so (b) is a harness sanity
check, and the decision rides on (a) alone: the DMA engines' per-row
issue rate either clears XLA's measured 16-19 GB/s gather floor
(>= ~75 M rows/s effective at 256 B rows) or it does not.  A loss is
recorded as a polish_ab-style negative and sequential stays; there is
no quality arm to trade because the two modes are bit-identical
(tests/test_polish_stream.py).

No accelerator was reachable in round 8, so this tool is the HARDWARE
RECIPE (run it on the next TPU session; POLISH_r08.json carries the
modeled projection it will confirm or kill).  On CPU it still runs the
`--verify` arm: interpret-mode bit-identity of the full matcher path
across modes — the measured correctness cell POLISH_r08.json quotes.

    python tools/polish_stream_ab.py [size]          # TPU A/B
    python tools/polish_stream_ab.py --verify [size] # CPU bit-identity
"""

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax.numpy as jnp

from image_analogies_tpu.utils.cache import enable_compilation_cache

enable_compilation_cache()

from image_analogies_tpu import SynthConfig, create_image_analogy, psnr
from image_analogies_tpu.utils.examples import super_resolution
from image_analogies_tpu.utils.kernelbench import sync as _sync


def _set_mode(mode: str):
    import image_analogies_tpu.models.analogy as an
    import image_analogies_tpu.models.patchmatch as pm

    pm._POLISH_MODE = mode
    an._level_fn.cache_clear()
    an._em_step_fn.cache_clear()


def verify(size: int) -> dict:
    """Interpret-mode bit-identity of the WHOLE matcher path across
    modes (CPU-runnable) — the same contract
    tests/test_polish_stream.py pins, re-measured here so the round
    artifact quotes a tool run, not only a test name."""
    a, ap, b = super_resolution(size)
    a, ap, b = (jnp.asarray(x, jnp.float32) for x in (a, ap, b))
    cfg = SynthConfig(
        levels=2, matcher="patchmatch", pallas_mode="interpret",
        em_iters=1, pm_iters=2, pm_polish_iters=1,
    )
    outs = {}
    for mode in ("sequential", "stream"):
        _set_mode(mode)
        aux = create_image_analogy(a, ap, b, cfg, return_aux=True)
        outs[mode] = (
            np.asarray(aux["bp"]),
            np.asarray(aux["dist"][0]),
        )
    _set_mode(os.environ.get("IA_POLISH_MODE", "sequential"))
    bp_eq = bool((outs["sequential"][0] == outs["stream"][0]).all())
    d_eq = bool((outs["sequential"][1] == outs["stream"][1]).all())
    return {
        "arm": "verify",
        "size": size,
        "backend": "cpu-interpret",
        "bp_bit_identical": bp_eq,
        "dist_bit_identical": d_eq,
    }


def measure(mode: str, a, ap, b) -> dict:
    _set_mode(mode)
    cfg = SynthConfig(
        levels=5, matcher="patchmatch", em_iters=2, pm_iters=6,
        pm_polish_iters=1,
    )
    run = lambda: create_image_analogy(a, ap, b, cfg)  # noqa: E731
    _sync(run())  # compile
    walls, out = [], None
    for _ in range(5):
        t0 = time.perf_counter()
        out = run()
        _sync(out)
        walls.append(round(time.perf_counter() - t0, 4))
    seeds_psnr = []
    for seed in (0, 1, 2):
        cfg_s = SynthConfig(
            levels=5, matcher="patchmatch", em_iters=2, pm_iters=6,
            pm_polish_iters=1, seed=seed,
        )
        o = np.asarray(create_image_analogy(a, ap, b, cfg_s))
        seeds_psnr.append(round(psnr(o, _ORACLE), 2))
    return {
        "mode": mode,
        "wall_median_s": statistics.median(walls),
        "wall_runs_s": walls,
        "psnr_seeds_db": seeds_psnr,
        "psnr_min_db": min(seeds_psnr),
    }


def main():
    args = [x for x in sys.argv[1:] if x != "--verify"]
    size = int(args[0]) if args else 1024
    if "--verify" in sys.argv:
        print(json.dumps(verify(min(size, 128))), flush=True)
        return
    global _ORACLE
    a, ap, b = super_resolution(size)
    a, ap, b = (jnp.asarray(x, jnp.float32) for x in (a, ap, b))
    for x in (a, ap, b):
        _sync(x)
    opath = os.path.join(
        os.path.dirname(__file__), "_oracle_out", f"oracle_f32_{size}.npy"
    )
    if os.path.exists(opath):
        _ORACLE = np.load(opath)
    else:
        _ORACLE = np.asarray(create_image_analogy(
            a, ap, b, SynthConfig(levels=5, matcher="brute", em_iters=2)
        ))
    res = {
        "size": size,
        "sequential": measure("sequential", a, ap, b),
        "stream": measure("stream", a, ap, b),
        "kill_criterion": (
            "stream ships iff wall_median(stream) < wall_median("
            "sequential) at the 1024^2 headline; PSNR is bit-pinned "
            "equal, so the decision is wall-only"
        ),
    }
    s, t = res["sequential"], res["stream"]
    res["delta"] = {
        "wall_s": round(t["wall_median_s"] - s["wall_median_s"], 4),
        "psnr_min_db": round(t["psnr_min_db"] - s["psnr_min_db"], 2),
    }
    res["decision"] = (
        "stream" if t["wall_median_s"] < s["wall_median_s"]
        else "sequential"
    )
    _set_mode(os.environ.get("IA_POLISH_MODE", "sequential"))
    print(json.dumps(res), flush=True)


if __name__ == "__main__":
    main()
