#!/usr/bin/env python
"""Measure the round-23 durable-telemetry acceptance cells into
ARCHIVE_r23.json.

Three subprocess arms against real `ia-synth serve` daemons on the
24px proxy, reusing the chaos harness's spawn/burst plumbing
(tools/chaos_serve.py):

  restart_continuity  boot 1 runs with `--baseline` + `--archive-dir`,
                      serves traffic, drains gracefully; boot 2 gets
                      ONLY `--archive-dir` and must resume the
                      anomaly baseline from disk (latency watch grades
                      — never no_data), stamp a strictly later
                      observatory generation, and render the restart
                      lineage through `ia-synth history`.
  incident_capture    a deliberately impossible baseline makes the
                      latency watch fire on the first graded window;
                      the black box must capture EXACTLY ONE bundle
                      (later ticks rate-limited, counted as
                      suppressed) containing every required section,
                      renderable by `ia-synth incident <id>`, with the
                      trigger->bundle latency measured.
  archive_torn_reload the SIGKILL-mid-append chaos arm, imported from
                      tools/chaos_serve.py: a torn half-line on disk
                      must be skipped AND counted on reload, with
                      baselines still resuming.

The headline `archive_overhead_frac` cell is the LARGEST live
`overhead_frac` any drilled daemon reported on `GET /archive`
(cumulative seconds inside archive writes over process wall — the
same measurement the `ia_archive_overhead_frac` gauge publishes and
the sentinel pins), held under the shared 2% telemetry budget by
tools/check_archive.py and trended by tools/check_trajectory.py.

Usage:
    JAX_PLATFORMS=cpu python tools/archive_drill.py \
        [--out ARCHIVE_r23.json] [--size 24]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
)

import chaos_serve as cs  # noqa: E402 - path bootstrap above

ARCHIVE_DRILL_SCHEMA_VERSION = 1

# Sections an incident bundle must carry to be a self-contained crime
# scene (serving/daemon.py `_incident_bundle` + the store's stamps).
BUNDLE_REQUIRED_KEYS = (
    "id", "ts", "trigger", "flight", "access_tail", "obs_window",
    "slo", "anomaly", "serving", "fingerprint",
)

# Fast archive/observatory cadence so a drill boot snapshots within a
# second instead of the serving defaults (30 s / 5 s).
_ARCHIVE_FLAGS = ["--archive-interval-s", "0.2", "--obs-interval-s",
                  "0.2", "--drain-deadline-s", "60"]


def _baseline_record(path: str, p99_ms: float) -> str:
    with open(path, "w") as f:
        json.dump({"pipeline": {"p99_warm_ms": p99_ms}}, f)
    return path


def _drain(url: str) -> int:
    req = urllib.request.Request(
        url + "/drain", data=b"{}", method="POST"
    )
    with urllib.request.urlopen(req, timeout=60) as resp:
        return resp.status


def _latency_watch(slo_doc: dict):
    for w in (slo_doc.get("anomalies") or {}).get("watches") or []:
        if w.get("watch") == "latency_p99":
            return w
    return None


def _cli(args, timeout=120):
    """One `ia-synth` CLI subprocess; returns (rc, stdout)."""
    proc = subprocess.run(
        [sys.executable, "-m", "image_analogies_tpu.cli", *args],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=timeout,
    )
    return proc.returncode, proc.stdout


def _arm_restart_continuity(a_path, ap_path, size):
    _, _, frames = cs._proxy_frames(size, 3)
    state = tempfile.mkdtemp(prefix="ia_drill_cont_s_")
    arch = tempfile.mkdtemp(prefix="ia_drill_cont_a_")
    traces = [tempfile.mkdtemp(prefix="ia_drill_cont_t_")
              for _ in range(2)]
    base = _baseline_record(
        os.path.join(state, "baseline.json"), 50.0
    )
    arm = {"name": "restart_continuity", "baseline_p99_ms": 50.0}
    p1 = p2 = None
    try:
        p1, u1 = cs._spawn_serve(
            a_path, ap_path, traces[0], state_dir=state,
            extra=[*_ARCHIVE_FLAGS, "--archive-dir", arch,
                   "--baseline", base],
        )
        for f in frames[:2]:
            cs._post(u1, cs._body(f))
        time.sleep(0.6)  # >= 2 archive snapshots at the 0.2 s cadence
        snap1 = cs._get_json(u1 + "/archive")
        arm["boot1_records"] = snap1.get("records")
        arm["boot1_overhead_frac"] = snap1.get("overhead_frac")
        arm["drain_status"] = _drain(u1)
        p1.wait(timeout=120)
        arm["boot1_exit_code"] = p1.returncode

        # Boot 2: NO --baseline.  Everything it grades against must
        # come off the archive.
        p2, u2 = cs._spawn_serve(
            a_path, ap_path, traces[1], state_dir=state,
            extra=[*_ARCHIVE_FLAGS, "--archive-dir", arch],
        )
        snap2 = cs._get_json(u2 + "/archive")
        resumed = snap2.get("resumed") or {}
        arm.update({
            "resumed_records": resumed.get("records"),
            "resumed_boots": resumed.get("boots"),
            "resumed_generation": resumed.get("generation"),
            "obs_generation": snap2.get("obs_generation"),
            "baseline_resumed": bool(
                snap2.get("anomaly_baseline_p99_ms") == 50.0
            ),
            "generation_monotonic": bool(
                isinstance(resumed.get("generation"), int)
                and isinstance(snap2.get("obs_generation"), int)
                and snap2["obs_generation"] > resumed["generation"]
            ),
        })
        cs._post(u2, cs._body(frames[2]))
        time.sleep(0.8)  # two obs ticks: the window needs >= 2 snaps
        watch = _latency_watch(cs._get_json(u2 + "/slo"))
        arm["post_restart_watch"] = watch
        arm["watch_graded"] = bool(
            watch is not None and watch.get("status") != "no_data"
        )
        arm["boot2_overhead_frac"] = cs._get_json(
            u2 + "/archive"
        ).get("overhead_frac")
        arm["drain2_status"] = _drain(u2)
        p2.wait(timeout=120)
        arm["boot2_exit_code"] = p2.returncode

        # The lineage must RENDER: `ia-synth history` over the same
        # archive dir shows both boots (json mode for the assertion).
        rc, out = _cli(["history", "--archive-dir", arch,
                        "--format", "json"])
        arm["history_rc"] = rc
        try:
            arm["history_boots"] = len(json.loads(out).get("boots", []))
        except ValueError:
            arm["history_boots"] = None
        arm["baseline_continuity"] = float(
            arm["baseline_resumed"] and arm["watch_graded"]
            and arm["generation_monotonic"]
            and rc == 0 and (arm["history_boots"] or 0) >= 2
        )
        return arm
    finally:
        for p in (p1, p2):
            if p is not None:
                cs._reap(p)
        for d in (state, arch, *traces):
            shutil.rmtree(d, ignore_errors=True)


def _arm_incident_capture(a_path, ap_path, size):
    _, _, frames = cs._proxy_frames(size, 2)
    state = tempfile.mkdtemp(prefix="ia_drill_inc_s_")
    arch = tempfile.mkdtemp(prefix="ia_drill_inc_a_")
    trace = tempfile.mkdtemp(prefix="ia_drill_inc_t_")
    # A baseline no real request can meet: the latency watch fires on
    # the first window that grades, which is the black-box trigger.
    base = _baseline_record(
        os.path.join(state, "baseline.json"), 0.001
    )
    arm = {"name": "incident_capture", "baseline_p99_ms": 0.001}
    proc = None
    try:
        proc, url = cs._spawn_serve(
            a_path, ap_path, trace, state_dir=state,
            extra=[*_ARCHIVE_FLAGS, "--archive-dir", arch,
                   "--baseline", base],
        )
        for f in frames:
            cs._post(url, cs._body(f))
        t0 = time.monotonic()
        captured = 0
        deadline = t0 + 30
        while time.monotonic() < deadline:
            idx = cs._get_json(url + "/incidents")
            captured = idx.get("captured", 0)
            if captured >= 1:
                break
            time.sleep(0.1)
        arm["capture_latency_ms"] = round(
            (time.monotonic() - t0) * 1000.0, 3
        )
        # Let several more firing ticks elapse: the episode stays hot,
        # the store must rate-limit every one of them.
        time.sleep(1.5)
        idx = cs._get_json(url + "/incidents")
        arm["captured"] = idx.get("captured")
        arm["suppressed"] = idx.get("suppressed")
        arm["rate_limited"] = bool(
            idx.get("captured") == 1 and idx.get("suppressed", 0) >= 1
        )
        incidents = idx.get("incidents") or []
        arm["trigger_kind"] = (
            incidents[0].get("trigger_kind") if incidents else None
        )
        inc_id = incidents[0]["id"] if incidents else None
        arm["incident_id"] = inc_id
        missing = []
        if inc_id:
            bundle = cs._get_json(
                f"{url}/incidents?id={inc_id}"
            )
            missing = [
                k for k in BUNDLE_REQUIRED_KEYS
                if bundle.get(k) is None
            ]
            arm["access_tail_len"] = len(bundle.get("access_tail")
                                         or [])
            arm["flight_events"] = len(
                (bundle.get("flight") or {}).get("events") or []
            )
            # The bundle must RENDER, live and from disk — the whole
            # point of a black box is being readable after the crash.
            arm["render_url_rc"] = _cli(
                ["incident", inc_id, "--url", url]
            )[0]
            arm["render_disk_rc"] = _cli(
                ["incident", inc_id, "--archive-dir", arch]
            )[0]
        arm["bundle_missing_keys"] = missing
        arm["capture_completeness"] = float(
            inc_id is not None and not missing
            and arm.get("render_url_rc") == 0
            and arm.get("render_disk_rc") == 0
        )
        arm["overhead_frac"] = cs._get_json(
            url + "/archive"
        ).get("overhead_frac")
        arm["drain_status"] = _drain(url)
        proc.wait(timeout=120)
        arm["exit_code"] = proc.returncode
        return arm
    finally:
        if proc is not None:
            cs._reap(proc)
        for d in (state, arch, trace):
            shutil.rmtree(d, ignore_errors=True)


def run_archive_drill(size: int = 24):
    from image_analogies_tpu.utils.io import save_image

    a, ap, _ = cs._proxy_frames(size, 0)
    asset_dir = tempfile.mkdtemp(prefix="ia_drill_assets_")
    a_path = os.path.join(asset_dir, "a.png")
    ap_path = os.path.join(asset_dir, "ap.png")
    save_image(a_path, a)
    save_image(ap_path, ap)
    try:
        cont = _arm_restart_continuity(a_path, ap_path, size)
        inc = _arm_incident_capture(a_path, ap_path, size)
        torn = cs._arm_archive_torn(a_path, ap_path, size)
    finally:
        shutil.rmtree(asset_dir, ignore_errors=True)

    overheads = [
        v for v in (
            cont.get("boot1_overhead_frac"),
            cont.get("boot2_overhead_frac"),
            inc.get("overhead_frac"),
        ) if isinstance(v, (int, float))
    ]
    return {
        "schema_version": ARCHIVE_DRILL_SCHEMA_VERSION,
        "kind": "archive_drill",
        "round": 23,
        "generated_by": "tools/archive_drill.py",
        "proxy_size": size,
        "config": {
            "levels": 2, "matcher": "patchmatch", "em_iters": 1,
            "pm_iters": 2, "max_batch": 1,
            "archive_interval_s": 0.2, "obs_interval_s": 0.2,
        },
        # Headline cells tools/check_trajectory.py trends.
        "baseline_continuity": cont.get("baseline_continuity", 0.0),
        "capture_completeness": inc.get("capture_completeness", 0.0),
        "captured_bundles": inc.get("captured"),
        "capture_latency_ms": inc.get("capture_latency_ms"),
        "archive_overhead_frac": (
            max(overheads) if overheads else None
        ),
        "torn_reload_clean": float(bool(
            torn.get("reload_clean")
            and torn.get("baseline_resumed")
            and torn.get("post_restart_request_ok")
        )),
        "generation_monotonic": float(bool(
            cont.get("generation_monotonic")
            and torn.get("generation_monotonic")
        )),
        "arms": [cont, inc, torn],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="ARCHIVE_r23.json")
    ap.add_argument("--size", type=int, default=24)
    args = ap.parse_args(argv)
    record = run_archive_drill(args.size)
    with open(args.out, "w") as f:
        json.dump(record, f, indent=1)
        f.write("\n")
    for arm in record["arms"]:
        keys = [
            k for k in (
                "baseline_continuity", "capture_completeness",
                "captured", "suppressed", "capture_latency_ms",
                "reload_clean", "baseline_resumed",
                "generation_monotonic", "skipped_lines",
            ) if k in arm
        ]
        print(
            f"{arm['name']:>22}: "
            + ", ".join(f"{k}={arm[k]}" for k in keys)
        )
    print(
        f"wrote {args.out} (continuity="
        f"{record['baseline_continuity']}, completeness="
        f"{record['capture_completeness']}, overhead_frac="
        f"{record['archive_overhead_frac']})"
    )
    from check_archive import validate_archive

    errs = validate_archive(record)
    for e in errs:
        print(f"archive_drill: VIOLATION: {e}", file=sys.stderr)
    return 1 if errs else 0


if __name__ == "__main__":
    sys.exit(main())
