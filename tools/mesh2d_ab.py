#!/usr/bin/env python
"""Hardware A/B recipe for the 2-D bands x slabs mesh (round 17), the
way tools/layout_ab.py recorded the layout decision: both arms under
one harness, and the kill criterion stated BEFORE the run.

Kill criterion (pre-stated, WALL-ONLY): at every probed size where the
planner chooses n_bands > 1 while the flat 1-D mesh still fits the
per-chip HBM budget, the 2-D warm wall must stay within 1.10x the 1-D
warm wall (min of --runs warm runs each).  If any such size breaks
that bound, the verdict is KILL: the planner must then choose bands
ONLY under HBM pressure (pass hbm_bytes and nothing else — the
residency constraint still un-caps A, but bands stop competing on
modeled bytes).  Quality is OUT of the criterion by construction:
kappa=0 bit-identity between the 2-D and 1-D runners is test-pinned
(tests/test_spatial.py), and this script re-checks it as a harness
sanity gate, not as a trade axis — a bit divergence aborts the A/B as
invalid rather than entering the verdict.

Sizes where the 1-D mesh does NOT fit HBM have no A arm to lose to:
they report the 2-D wall alone (that is the un-cap, not a race).

Run on the TPU box:
    python tools/mesh2d_ab.py --sizes 4096 8192 [--runs 3] \
        [--hbm-gib 16] [--out MESH2D_AB.json]

On CPU (no accelerator) the walls are interpret-mode proxies; the
artifact records platform so nobody mistakes them for chip numbers.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

KILL_WALL_RATIO = 1.10


def _ab_one(size: int, runs: int, hbm_bytes: int) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from image_analogies_tpu import SynthConfig
    from image_analogies_tpu.parallel.mesh import make_mesh
    from image_analogies_tpu.parallel.plan2d import plan_mesh_shape
    from image_analogies_tpu.parallel.spatial import synthesize_spatial
    from image_analogies_tpu.utils.examples import super_resolution

    platform = jax.devices()[0].platform
    n_dev = len(jax.devices())
    kw = dict(levels=1, matcher="patchmatch", em_iters=2, pm_iters=2)
    if platform == "cpu":
        kw["pallas_mode"] = "interpret"
    cfg = SynthConfig(**kw)
    a, ap, b = super_resolution(size)
    a, ap, b = (jnp.asarray(x, jnp.float32) for x in (a, ap, b))

    plan = plan_mesh_shape(
        n_dev, a.shape[:2], b.shape[:2], cfg, hbm_bytes=hbm_bytes
    )
    flat = plan_mesh_shape(n_dev, a.shape[:2], b.shape[:2], cfg)
    flat_fits = any(
        c.n_bands == 1 and c.feasible and (
            hbm_bytes is None or c.residency_bytes <= hbm_bytes
        )
        for c in (flat.chosen, *flat.rejected)
    )

    def timed(mesh, mp):
        out = synthesize_spatial(a, ap, b, cfg, mesh, mesh_plan=mp)
        jax.block_until_ready(out)          # compile run
        walls = []
        for _ in range(runs):
            t0 = time.perf_counter()
            out = synthesize_spatial(a, ap, b, cfg, mesh, mesh_plan=mp)
            jax.block_until_ready(out)
            walls.append(round(time.perf_counter() - t0, 3))
        return np.asarray(out), walls

    mesh2d = make_mesh(
        n_dev, axis_names=("bands", "slabs"),
        shape=(plan.n_bands, plan.n_slabs),
    )
    out_2d, walls_2d = timed(mesh2d, plan.as_attrs())
    row = {
        "size": size,
        "mesh_shape": [plan.n_bands, plan.n_slabs],
        "wall_2d_s": min(walls_2d),
        "wall_2d_runs_s": walls_2d,
        "flat_fits_hbm": flat_fits,
        "banded": plan.n_bands > 1,
    }
    if not flat_fits:
        row["verdict"] = "uncapped"     # nothing to race: 1-D cannot run
        return row
    out_1d, walls_1d = timed(make_mesh(n_dev), None)
    row["wall_1d_s"] = min(walls_1d)
    row["wall_1d_runs_s"] = walls_1d
    # Harness sanity gate, NOT a trade axis (see module docstring).
    # 1-D at n_dev slabs only matches bit-for-bit when both arms run
    # the same slab count; with bands > 1 the arms differ in slab
    # count, so the gate compares against 1-D at plan.n_slabs.
    ref, _ = timed(make_mesh(plan.n_slabs), None)
    if not np.array_equal(out_2d, ref):
        raise SystemExit(
            f"mesh2d_ab: size {size}: 2-D output diverged from the "
            "1-D runner at the same slab count — A/B invalid, fix the "
            "miscompile before measuring anything"
        )
    ratio = row["wall_2d_s"] / max(row["wall_1d_s"], 1e-9)
    row["wall_ratio_2d_over_1d"] = round(ratio, 3)
    if plan.n_bands > 1:
        row["verdict"] = (
            "keep" if ratio <= KILL_WALL_RATIO else "KILL"
        )
    else:
        row["verdict"] = "no-contest"   # planner chose flat anyway
    return row


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--sizes", type=int, nargs="+", required=True)
    ap.add_argument("--runs", type=int, default=3)
    ap.add_argument(
        "--hbm-gib", type=float, default=16.0,
        help="per-chip HBM budget the planner is held to (v5e: 16)",
    )
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)
    import jax

    hbm = int(args.hbm_gib * (1 << 30))
    rows = [_ab_one(s, args.runs, hbm) for s in sorted(args.sizes)]
    verdicts = [r.get("verdict") for r in rows]
    record = {
        "kill_criterion": (
            f"wall-only: 2-D wall <= {KILL_WALL_RATIO}x 1-D wall at "
            "every size where bands engaged while flat still fit "
            f"{args.hbm_gib} GiB HBM; quality excluded by the "
            "test-pinned kappa=0 bit-identity (sanity-gated here)"
        ),
        "platform": jax.devices()[0].platform,
        "n_devices": len(jax.devices()),
        "rows": rows,
        "verdict": "KILL" if "KILL" in verdicts else "keep",
    }
    text = json.dumps(record, indent=1)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    print(text, flush=True)
    return 1 if record["verdict"] == "KILL" else 0


if __name__ == "__main__":
    sys.exit(main())
